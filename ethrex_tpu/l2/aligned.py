"""Aligned-mode proving: batched proof aggregation with an L1ProofVerifier.

Mirrors the reference's aligned deployment mode (crates/l2/sequencer/
l1_proof_verifier.rs:66; docs/l2/deployment/aligned_failure_recovery.md):
instead of posting each batch proof directly, proofs are SUBMITTED to an
aggregation layer, and a separate verifier actor polls until the
aggregated verification lands, resubmitting after a timeout.  The
`AlignedLayer` here is an in-process stand-in for the external service —
it checks the submitted proofs with the registered backends and reports
inclusion after a configurable number of polls (so tests exercise the
pending -> included and pending -> expired -> resubmit paths
deterministically).
"""

from __future__ import annotations

import json as _json
import threading
import time

from ..prover.backend import get_backend


class AlignedLayer:
    """In-process aggregation service stand-in.

    Submissions become `included` after `latency_polls` status polls
    (simulating the aggregation epoch), unless `fail_every` marks them
    lost (simulating a dropped aggregation — the resubmission path).
    """

    PENDING, INCLUDED, LOST = "pending", "included", "lost"

    def __init__(self, latency_polls: int = 2, fail_every: int = 0):
        self.latency_polls = latency_polls
        self.fail_every = fail_every
        self.submissions: dict[int, dict] = {}
        self._next_id = 0
        self._submit_count = 0
        self.lock = threading.RLock()

    def submit(self, first: int, last: int, proofs: dict,
               expected_modes: dict | None = None) -> int:
        """Validate and enqueue an aggregation request; returns its id.

        `expected_modes` (batch number -> committer-derived vm mode)
        hardens against mode downgrades: a claimed-log tpu proof for a
        batch the VM circuits cover is rejected here, before it can
        settle (review finding — the stand-in previously accepted the
        weak form)."""
        with self.lock:
            for prover_type, batch_proofs in proofs.items():
                backend = get_backend(prover_type)
                for i, proof in enumerate(batch_proofs):
                    if expected_modes is not None and \
                            not backend.check_coverage(
                                proof, expected_modes.get(first + i, "")):
                        raise ValueError(
                            f"aligned: {prover_type} proof for batch "
                            f"{first + i} downgrades its vm coverage")
                    if not backend.verify(proof):
                        raise ValueError(
                            f"aligned: invalid {prover_type} proof")
            self._submit_count += 1
            lost = (self.fail_every
                    and self._submit_count % self.fail_every == 0)
            sid = self._next_id
            self._next_id += 1
            self.submissions[sid] = {
                "range": (first, last), "polls": 0,
                "state": self.LOST if lost else self.PENDING,
            }
            return sid

    def status(self, sid: int) -> str:
        with self.lock:
            sub = self.submissions.get(sid)
            if sub is None:
                return self.LOST
            if sub["state"] == self.PENDING:
                sub["polls"] += 1
                if sub["polls"] >= self.latency_polls:
                    sub["state"] = self.INCLUDED
            return sub["state"]


class L1ProofVerifier:
    """Tracks aligned submissions and finalizes them on the L1.

    One `step()` per timer tick (the sequencer loop drives it):
      1. collect the next run of consecutive committed+fully-proven
         batches (same predicate as the direct L1ProofSender path);
      2. submit them to the aligned layer if not already in flight;
      3. poll the in-flight submission: included -> verify_batches on the
         L1 and mark verified; lost or timed out -> resubmit.
    """

    def __init__(self, rollup, l1, aligned: AlignedLayer,
                 needed_prover_types: list[str],
                 resubmit_timeout: float = 30.0,
                 aggregate: bool = False, min_aggregate: int = 2):
        self.rollup = rollup
        self.l1 = l1
        self.aligned = aligned
        self.needed = list(needed_prover_types)
        self.resubmit_timeout = resubmit_timeout
        self.aggregate = aggregate
        self.min_aggregate = max(1, min_aggregate)
        self.inflight: dict | None = None

    def _collect(self):
        first = self.l1.last_verified_batch() + 1
        last = first - 1
        while True:
            batch = self.rollup.get_batch(last + 1)
            if batch is None or not batch.committed:
                break
            if not self.rollup.batch_fully_proven(last + 1, self.needed):
                break
            last += 1
        if last < first:
            return None
        proofs = {
            t: [self.rollup.get_proof(n, t)
                for n in range(first, last + 1)]
            for t in self.needed
        }
        return first, last, proofs

    def _expected_modes(self, first, last):
        modes = {}
        for n in range(first, last + 1):
            batch = self.rollup.get_batch(n)
            if batch is not None:
                modes[n] = batch.vm_mode
        return modes

    def _submit(self, first, last, proofs):
        sid = self.aligned.submit(first, last, proofs,
                                  self._expected_modes(first, last))
        self.inflight = {"sid": sid, "first": first, "last": last,
                         "proofs": proofs, "submitted_at": time.time()}

    def step(self) -> str | None:
        if self.inflight is None:
            work = self._collect()
            if work is None:
                return None
            self._submit(*work)
            return "submitted"
        sid = self.inflight["sid"]
        state = self.aligned.status(sid)
        if state == AlignedLayer.INCLUDED:
            first, last = self.inflight["first"], self.inflight["last"]
            if self.aggregate and last - first + 1 >= self.min_aggregate:
                # the aligned layer already verified every full proof at
                # submit time, so settlement only needs the committed
                # outputs: one outputs-bundle payload per type, one L1 tx
                # for the whole range (docs/AGGREGATION.md)
                from . import aggregator as agg_mod

                wire = {
                    t: _json.dumps(agg_mod.bundle_payload(
                        [agg_mod.slim_entry(p) for p in plist],
                        first, last), separators=(",", ":")).encode()
                    for t, plist in self.inflight["proofs"].items()
                }
                self.l1.verify_batches_aggregated(first, last, wire)
            else:
                wire = {
                    t: [get_backend(t).to_proof_bytes(p) for p in plist]
                    for t, plist in self.inflight["proofs"].items()
                }
                self.l1.verify_batches(first, last, wire)
            for n in range(first, last + 1):
                self.rollup.set_verified(n)
            self.inflight = None
            return "verified"
        timed_out = (time.time() - self.inflight["submitted_at"]
                     > self.resubmit_timeout)
        if state == AlignedLayer.LOST or timed_out:
            # resubmission path (aligned_failure_recovery.md:98)
            work = (self.inflight["first"], self.inflight["last"],
                    self.inflight["proofs"])
            self.inflight = None
            self._submit(*work)
            return "resubmitted"
        return "pending"
