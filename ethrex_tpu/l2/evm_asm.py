"""Tiny EVM assembler: mnemonic streams with labels -> runtime bytecode.

Used to build the settlement contracts' bytecode in-repo (no solc in the
toolchain): l2/proposer_evm.py assembles the OnChainProposer state
machine from the rule-for-rule port in l2/proposer_rules.py, and the L2
integration tests settle through the resulting code executed by our own
EVM (reference seat: crates/l2/contracts/src/l1/OnChainProposer.sol +
the deployer, cmd/ethrex/l2/deployer.rs).

Instruction stream items:
  "MNEMONIC"              plain opcode
  ("PUSH", int|bytes)     smallest PUSHk fitting the value
  ("PUSHL", "label")      PUSH2 placeholder patched to the label offset
  ("LABEL", "name")       defines a jump target (emits JUMPDEST)
"""

from __future__ import annotations

OPS = {
    "STOP": 0x00, "ADD": 0x01, "MUL": 0x02, "SUB": 0x03, "DIV": 0x04,
    "MOD": 0x06, "LT": 0x10, "GT": 0x11, "EQ": 0x14, "ISZERO": 0x15,
    "AND": 0x16, "OR": 0x17, "XOR": 0x18, "NOT": 0x19, "SHL": 0x1B,
    "SHR": 0x1C, "KECCAK256": 0x20, "ADDRESS": 0x30, "CALLER": 0x33,
    "CALLVALUE": 0x34, "CALLDATALOAD": 0x35, "CALLDATASIZE": 0x36,
    "POP": 0x50, "MLOAD": 0x51, "MSTORE": 0x52, "SLOAD": 0x54,
    "SSTORE": 0x55, "JUMP": 0x56, "JUMPI": 0x57, "JUMPDEST": 0x5B,
    "RETURN": 0xF3, "REVERT": 0xFD, "STATICCALL": 0xFA, "GAS": 0x5A,
    "RETURNDATASIZE": 0x3D, "RETURNDATACOPY": 0x3E,
}
for _i in range(1, 17):
    OPS[f"DUP{_i}"] = 0x80 + _i - 1
    OPS[f"SWAP{_i}"] = 0x90 + _i - 1


def assemble(items: list) -> bytes:
    """Two-pass assembly with 2-byte label operands."""
    # pass 1: offsets
    offsets: dict[str, int] = {}
    pc = 0
    for it in items:
        if isinstance(it, str):
            pc += 1
        elif it[0] == "LABEL":
            offsets[it[1]] = pc
            pc += 1                      # JUMPDEST
        elif it[0] == "PUSHL":
            pc += 3                      # PUSH2 xx xx
        elif it[0] == "PUSH":
            pc += 1 + len(_imm(it[1]))
        else:
            raise ValueError(f"bad asm item {it!r}")
    # pass 2: emit
    out = bytearray()
    for it in items:
        if isinstance(it, str):
            out.append(OPS[it])
        elif it[0] == "LABEL":
            out.append(OPS["JUMPDEST"])
        elif it[0] == "PUSHL":
            target = offsets[it[1]]
            out += bytes([0x61, target >> 8, target & 0xFF])
        else:
            imm = _imm(it[1])
            out += bytes([0x5F + len(imm)]) + imm
    return bytes(out)


def _imm(v) -> bytes:
    if isinstance(v, bytes):
        return v if v else b""
    v = int(v)
    if v == 0:
        return b""                       # PUSH0
    return v.to_bytes((v.bit_length() + 7) // 8, "big")
