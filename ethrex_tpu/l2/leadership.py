"""Sequencer leadership: L1-fenced leader leases (docs/SEQUENCER_HA.md).

The design is Chubby's (Burrows, OSDI 2006; PAPERS.md): a single
coarse-grained lease lives in a compare-and-swap cell on the L1
(`L1Client.acquire_lease` / `renew_lease` / `release_lease`), and every
acquisition mints a fresh **epoch** — a monotonically increasing fencing
token.  Whoever holds the lease is the leader; everything the leader
writes to shared state (L1 commit/verify transactions, rollup-store
batch-record write groups) carries its epoch, and both sinks reject
writes fenced below the highest epoch they have observed with a typed
:class:`FencedError`.  A zombie leader — paused mid-commit, deposed,
resumed — therefore cannot corrupt shared state: its delayed write is
rejected at the sink, it demotes itself, and re-enters candidacy.

Renewal runs on its own daemon thread at ``ttl/3`` with jitter (so two
standbys never stampede in lock-step); a leader that cannot renew past
the safety margin steps down *before* its lease can expire under a
competing candidate.  Promotion is deliberately nothing but the normal
crash-recovery startup path (Crash-Only Software, Candea & Fox 2003):
the ``on_promote`` callback runs PR-2 L1 reconciliation + PR-4 journal
replay and then unparks the actors.

Fault sites (utils/faults.py): ``l1.lease`` fires on both legs of every
acquire/renew (request lost vs response lost — the second leg leaves the
lease acquired on L1 while the candidate believes it failed), and
``seq.fence`` fires at each sequencer-side fence checkpoint.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass

from ..utils import faults, metrics

log = logging.getLogger("ethrex_tpu.l2.leadership")

# role strings are part of the ethrex_ready wire format
ROLE_FOLLOWER = "follower"
ROLE_CANDIDATE = "candidate"
ROLE_PROMOTING = "promoting"
ROLE_LEADER = "leader"

ROLES = (ROLE_FOLLOWER, ROLE_CANDIDATE, ROLE_PROMOTING, ROLE_LEADER)


class FencedError(Exception):
    """A write carried a stale leadership epoch and was refused.

    Raised by the L1 (commit/verify transactions) and by the rollup
    store (batch-record write groups) when the presented fencing token
    is below the highest epoch the sink has observed.  The sequencer
    treats this as "I have been deposed": demote, re-enter candidacy —
    never retry the write.
    """

    def __init__(self, message: str, epoch: int | None = None,
                 current: int | None = None):
        super().__init__(message)
        self.epoch = epoch
        self.current = current


@dataclass
class LeaseState:
    """One observation of the L1 lease cell (read-side view)."""

    holder: str | None
    epoch: int
    expires: float

    def to_json(self) -> dict:
        return {"holder": self.holder, "epoch": self.epoch,
                "expires": self.expires}


class LeadershipManager:
    """Drives one node's leadership lifecycle against the L1 lease cell.

    Roles: ``follower`` (parked, not seeking the lease — hot standby
    before its candidacy delay elapses), ``candidate`` (polling the CAS
    cell), ``promoting`` (lease won, running the crash-recovery startup
    path), ``leader`` (renewing at ttl/3).  ``on_promote`` /
    ``on_demote`` are supplied by the sequencer; exceptions from
    ``on_promote`` abort the promotion and release the lease so another
    candidate can win.
    """

    def __init__(self, l1, node_id: str, ttl: float = 3.0,
                 on_promote=None, on_demote=None,
                 safety_margin: float | None = None,
                 candidacy_delay: float = 0.0,
                 jitter: float = 0.25, rng_seed: int | None = None,
                 clock=time.monotonic):
        if ttl <= 0:
            raise ValueError("lease ttl must be positive")
        self.l1 = l1
        self.node_id = node_id
        self.ttl = float(ttl)
        # step down once this much of the ttl has passed without a
        # successful renewal (default: two missed renewal periods)
        self.safety_margin = (safety_margin if safety_margin is not None
                              else 2.0 * self.ttl / 3.0)
        self.candidacy_delay = float(candidacy_delay)
        self.jitter = jitter
        self.on_promote = on_promote
        self.on_demote = on_demote
        self.clock = clock
        self._rng = random.Random(rng_seed)
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._role = ROLE_FOLLOWER
        self._epoch: int | None = None
        self._last_renewal: float | None = None
        self.transitions_total = 0
        self.fenced_total = 0
        self.last_error: str | None = None
        self.promotion_downtime: float | None = None
        self.promoted_at: float | None = None
        metrics.record_leadership_role(self._role)

    # ---------------------------------------------------------------- state

    @property
    def role(self) -> str:
        return self._role

    @property
    def epoch(self) -> int | None:
        """The fencing token to stamp on writes; None while not leader."""
        with self._lock:
            return self._epoch if self._role in (ROLE_PROMOTING,
                                                 ROLE_LEADER) else None

    def is_leader(self) -> bool:
        return self._role == ROLE_LEADER

    def check(self):
        """Sequencer-side fence checkpoint: raise FencedError unless this
        node currently believes it is the (promoting) leader.  The
        ``seq.fence`` fault site injects deposition exactly here."""
        faults.inject("seq.fence")
        with self._lock:
            if self._role not in (ROLE_PROMOTING, ROLE_LEADER) or \
                    self._epoch is None:
                raise FencedError(
                    f"{self.node_id}: not the leader (role={self._role})",
                    epoch=self._epoch)
            return self._epoch

    def status(self) -> dict:
        """JSON-friendly view for ethrex_ready / health / monitor."""
        with self._lock:
            return {
                "role": self._role,
                "epoch": self._epoch,
                "transitions": self.transitions_total,
                "fenced": self.fenced_total,
                "leaseTtlSeconds": self.ttl,
                "promotionDowntimeSeconds": self.promotion_downtime,
                "lastError": self.last_error,
            }

    def leaderless(self) -> bool:
        """True when, from this node's view, nobody holds a live lease.
        Feeds the sequencer_leaderless alert pair."""
        if self._role in (ROLE_PROMOTING, ROLE_LEADER):
            return False
        try:
            lease = self.l1.get_lease()
        except Exception:  # noqa: BLE001 — an unreachable L1 is leaderless
            return True
        return lease is None or lease.expires <= self.clock()

    # ------------------------------------------------------------- lifecycle

    def start(self) -> "LeadershipManager":
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._loop, name=f"leadership-{self.node_id}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0):
        """Release the lease (if held) and join the lifecycle thread.
        Idempotent: safe to call repeatedly and before start()."""
        self._stop.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout)
        with self._lock:
            epoch = self._epoch
            was_leader = self._role in (ROLE_PROMOTING, ROLE_LEADER)
        if was_leader and epoch is not None:
            try:
                self.l1.release_lease(self.node_id, epoch)
            except Exception as e:  # noqa: BLE001 — lease expires anyway
                log.warning("lease release failed (will expire): %s", e)
        self._transition(ROLE_FOLLOWER, demote=was_leader)

    def step_down(self, reason: str = "stepped down"):
        """Voluntary demotion (renewal starvation or a FencedError from a
        sink): park the actors, drop the epoch, re-enter candidacy."""
        with self._lock:
            if self._role not in (ROLE_PROMOTING, ROLE_LEADER):
                return
            self.last_error = reason
        log.warning("%s: stepping down: %s", self.node_id, reason)
        self._transition(ROLE_CANDIDATE, demote=True)

    def fenced(self, err: FencedError):
        """A sink rejected our epoch — we are deposed, not failing."""
        self.fenced_total += 1
        metrics.record_leadership_fenced()
        self.step_down(f"fenced: {err}")

    def try_acquire(self) -> bool:
        """One synchronous candidacy step: attempt the CAS and, on
        success, run the FULL promotion path before returning.  The
        chaos battery (and any slow-poll caller) drives failover
        deterministically through this instead of the timer loop."""
        with self._lock:
            if self._role in (ROLE_PROMOTING, ROLE_LEADER):
                return True
            if self._role == ROLE_FOLLOWER:
                pass  # a manual bid skips the candidacy delay
        self._transition(ROLE_CANDIDATE)
        try:
            epoch = self._acquire()
        except Exception as e:  # noqa: BLE001 — L1 flake: bid again later
            self.last_error = f"acquire: {e}"
            return False
        if epoch is None:
            return False
        self._promote(epoch)
        return self._role == ROLE_LEADER

    # ------------------------------------------------------------- internals

    def _transition(self, role: str, demote: bool = False):
        with self._lock:
            prev = self._role
            if prev == role and not demote:
                return
            self._role = role
            if role not in (ROLE_PROMOTING, ROLE_LEADER):
                self._epoch = None
                self._last_renewal = None
            if prev != role:
                self.transitions_total += 1
                metrics.record_leadership_transition(prev, role)
                metrics.record_leadership_role(role)
                log.info("%s: %s -> %s", self.node_id, prev, role)
        if demote and self.on_demote is not None:
            try:
                self.on_demote()
            except Exception:  # noqa: BLE001 — demotion must not wedge
                log.exception("on_demote callback failed")

    def _acquire(self) -> int | None:
        """One CAS attempt, with the two-leg l1.lease fault site: leg 1
        loses the request, leg 2 loses the *response* (the lease is held
        on L1 but this candidate does not know — it must survive its own
        orphaned term expiring)."""
        faults.inject("l1.lease")
        epoch = self.l1.acquire_lease(self.node_id, self.ttl)
        faults.inject("l1.lease")
        return epoch

    def _renew(self, epoch: int) -> bool:
        faults.inject("l1.lease")
        ok = self.l1.renew_lease(self.node_id, epoch, self.ttl)
        faults.inject("l1.lease")
        return bool(ok)

    def _loop(self):
        clock = self.clock
        if self.candidacy_delay > 0:
            self._stop.wait(self.candidacy_delay)
        if not self._stop.is_set():
            self._transition(ROLE_CANDIDATE)
        while not self._stop.is_set():
            if self._role == ROLE_CANDIDATE:
                try:
                    epoch = self._acquire()
                except FencedError:
                    epoch = None
                except Exception as e:  # noqa: BLE001 — L1 flake: retry
                    self.last_error = f"acquire: {e}"
                    epoch = None
                if epoch is not None:
                    self._promote(epoch)
                    if self._role != ROLE_LEADER:
                        # failed promotion (reconciliation not possible
                        # yet, or fenced mid-flight): the lease was
                        # released, but do NOT spin on re-bidding — on a
                        # real L1 every acquire/release round is a pair
                        # of transactions.  Wait out a candidacy
                        # interval; the condition that failed the
                        # promotion (usually the DA replica lagging the
                        # committed tip) needs time to clear anyway.
                        self._stop.wait(self._jittered(self.ttl / 3.0))
                else:
                    # poll again before a live lease could expire
                    self._stop.wait(self._jittered(self.ttl / 3.0))
            elif self._role == ROLE_LEADER:
                self._stop.wait(self._jittered(self.ttl / 3.0))
                if self._stop.is_set() or self._role != ROLE_LEADER:
                    continue
                self._renew_or_step_down()
            else:  # demoted back to follower by an external stop()
                self._stop.wait(self._jittered(self.ttl / 3.0))
                if not self._stop.is_set() and self._role == ROLE_FOLLOWER:
                    self._transition(ROLE_CANDIDATE)

    def _promote(self, epoch: int):
        with self._lock:
            self._epoch = epoch
            self._last_renewal = self.clock()
        metrics.record_leadership_epoch(epoch)
        self._transition(ROLE_PROMOTING)
        t0 = self.clock()
        try:
            if self.on_promote is not None:
                self.on_promote()
        except FencedError as e:
            self.fenced(e)
            return
        except Exception as e:  # noqa: BLE001 — failed promotion yields
            log.exception("promotion failed; releasing lease")
            self.last_error = f"promote: {e}"
            try:
                self.l1.release_lease(self.node_id, epoch)
            except Exception:  # noqa: BLE001 — lease expires anyway
                pass
            self._transition(ROLE_CANDIDATE, demote=True)
            return
        with self._lock:
            self.promotion_downtime = self.clock() - t0
            self.promoted_at = time.time()
        metrics.record_leadership_promotion(self.promotion_downtime)
        self._transition(ROLE_LEADER)

    def _renew_or_step_down(self):
        with self._lock:
            epoch = self._epoch
            last = self._last_renewal
        if epoch is None:
            return
        try:
            ok = self._renew(epoch)
        except Exception as e:  # noqa: BLE001 — L1 flake counts as a miss
            self.last_error = f"renew: {e}"
            ok = False
        now = self.clock()
        if ok:
            with self._lock:
                self._last_renewal = now
            return
        # a single missed renewal is tolerated; past the safety margin
        # the lease may be expiring under us — step down BEFORE a
        # competing candidate can win it while we still write
        if last is not None and (now - last) >= self.safety_margin:
            self.step_down(
                f"renewal starved for {now - last:.2f}s "
                f"(safety margin {self.safety_margin:.2f}s)")

    def _jittered(self, base: float) -> float:
        return base * (1.0 + self.jitter * self._rng.random())
