"""One asyncio event loop on a daemon thread.

The node is thread-structured (producer thread, sequencer actors,
prover clients); the serving front door is event-driven (SEDA's
argument — Welsh et al., "SEDA: An Architecture for Well-Conditioned,
Scalable Internet Services", SOSP 2001; PAPERS.md): one loop multiplexes
thousands of connections, and blocking work crosses into a bounded
executor pool instead of a thread per connection.  This helper is the
bridge between the two worlds: it owns exactly one loop, runs it on a
daemon thread, and lets synchronous code submit coroutines and shut the
loop down deterministically (the leak checks in the overload soak count
threads and fds after stop()).
"""

from __future__ import annotations

import asyncio
import threading


class LoopThread:
    """An asyncio event loop running on a dedicated daemon thread.

    start() blocks until the loop is spinning; call() submits a
    coroutine from any thread and waits for its result; stop() cancels
    outstanding tasks, halts the loop, joins the thread and closes the
    loop so no selector fd outlives the server.
    """

    def __init__(self, name: str = "aio-loop"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._started = threading.Event()
        self._stopped = False

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self._started.set()
        try:
            self.loop.run_forever()
        finally:
            try:
                self.loop.close()
            except Exception:  # noqa: BLE001 — teardown must not raise
                pass

    def start(self) -> "LoopThread":
        self._thread.start()
        self._started.wait()
        return self

    def running(self) -> bool:
        return self._thread.is_alive() and not self._stopped

    def call(self, coro, timeout: float | None = 30.0):
        """Run `coro` on the loop from any thread; returns its result
        (or raises its exception) within `timeout` seconds."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        try:
            return fut.result(timeout)
        except BaseException:
            fut.cancel()
            raise

    def stop(self, timeout: float = 5.0):
        """Cancel every outstanding task, stop and close the loop."""
        if self._stopped or not self._thread.is_alive():
            self._stopped = True
            return
        self._stopped = True

        async def _cancel_all():
            tasks = [t for t in asyncio.all_tasks()
                     if t is not asyncio.current_task()]
            for task in tasks:
                task.cancel()
            if tasks:
                await asyncio.gather(*tasks, return_exceptions=True)

        try:
            asyncio.run_coroutine_threadsafe(
                _cancel_all(), self.loop).result(timeout)
        except Exception:  # noqa: BLE001 — a wedged task must not block
            pass           # shutdown; loop.close() below reclaims the fd
        try:
            self.loop.call_soon_threadsafe(self.loop.stop)
        except RuntimeError:
            pass
        self._thread.join(timeout)
