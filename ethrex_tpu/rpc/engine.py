"""Engine API: engine_newPayload / forkchoiceUpdated / getPayload + JWT
(parity with the reference's crates/networking/rpc/engine/{payload.rs,
fork_choice.rs} and authentication.rs)."""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time

from ..blockchain.blockchain import InvalidBlock
from ..blockchain.fork_choice import ForkChoiceError
from ..blockchain.payload import build_payload, create_payload_header
from ..primitives.block import (Block, BlockBody, BlockHeader, Withdrawal,
                                EMPTY_UNCLE_HASH)
from ..primitives.genesis import Fork
from ..primitives.transaction import Transaction
from .eth import CLIENT_NAME, CLIENT_VERSION, RpcError
from .serializers import hb, hx, parse_bytes, parse_quantity

VALID = "VALID"
INVALID = "INVALID"
SYNCING = "SYNCING"


# ---------------------------------------------------------------------------
# JWT (HS256, stdlib only)
# ---------------------------------------------------------------------------

def _b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def _b64url_encode(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).decode().rstrip("=")


def jwt_encode(secret: bytes, claims: dict | None = None) -> str:
    header = _b64url_encode(json.dumps(
        {"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64url_encode(json.dumps(
        claims or {"iat": int(time.time())}).encode())
    signing = f"{header}.{payload}".encode()
    sig = _b64url_encode(hmac.new(secret, signing, hashlib.sha256).digest())
    return f"{header}.{payload}.{sig}"


def jwt_verify(secret: bytes, token: str, max_drift: int = 60) -> bool:
    try:
        header_b64, payload_b64, sig_b64 = token.split(".")
        signing = f"{header_b64}.{payload_b64}".encode()
        expected = hmac.new(secret, signing, hashlib.sha256).digest()
        if not hmac.compare_digest(expected, _b64url_decode(sig_b64)):
            return False
        claims = json.loads(_b64url_decode(payload_b64))
        iat = int(claims.get("iat", 0))
        return abs(time.time() - iat) <= max_drift
    except (ValueError, KeyError, TypeError):
        return False


# ---------------------------------------------------------------------------
# payload <-> block conversion
# ---------------------------------------------------------------------------

def payload_to_block(p: dict, parent_beacon_block_root: str | None,
                     requests_hash: bytes | None = None) -> Block:
    txs = [Transaction.decode_canonical(parse_bytes(t))
           for t in p.get("transactions", [])]
    withdrawals = None
    if p.get("withdrawals") is not None:
        withdrawals = [
            Withdrawal(parse_quantity(w["index"]),
                       parse_quantity(w["validatorIndex"]),
                       parse_bytes(w["address"]),
                       parse_quantity(w["amount"]))
            for w in p["withdrawals"]]
    from ..blockchain.blockchain import (compute_tx_root,
                                         compute_withdrawals_root)
    header = BlockHeader(
        parent_hash=parse_bytes(p["parentHash"]),
        uncles_hash=EMPTY_UNCLE_HASH,
        coinbase=parse_bytes(p["feeRecipient"]),
        state_root=parse_bytes(p["stateRoot"]),
        tx_root=compute_tx_root(txs),
        receipts_root=parse_bytes(p["receiptsRoot"]),
        bloom=parse_bytes(p["logsBloom"]),
        difficulty=0,
        number=parse_quantity(p["blockNumber"]),
        gas_limit=parse_quantity(p["gasLimit"]),
        gas_used=parse_quantity(p["gasUsed"]),
        timestamp=parse_quantity(p["timestamp"]),
        extra_data=parse_bytes(p["extraData"]),
        prev_randao=parse_bytes(p["prevRandao"]),
        base_fee_per_gas=parse_quantity(p["baseFeePerGas"]),
    )
    if withdrawals is not None:
        header.withdrawals_root = compute_withdrawals_root(withdrawals)
    if p.get("blobGasUsed") is not None:
        header.blob_gas_used = parse_quantity(p["blobGasUsed"])
        header.excess_blob_gas = parse_quantity(p["excessBlobGas"])
    if parent_beacon_block_root is not None:
        header.parent_beacon_block_root = parse_bytes(
            parent_beacon_block_root)
    if requests_hash is not None:
        header.requests_hash = requests_hash
    body = BlockBody(transactions=txs, uncles=[], withdrawals=withdrawals)
    block = Block(header, body)
    if block.hash != parse_bytes(p["blockHash"]):
        raise RpcError(-32602, "block hash mismatch")
    return block


def block_to_payload(block: Block) -> dict:
    h = block.header
    out = {
        "parentHash": hb(h.parent_hash),
        "feeRecipient": hb(h.coinbase),
        "stateRoot": hb(h.state_root),
        "receiptsRoot": hb(h.receipts_root),
        "logsBloom": hb(h.bloom),
        "prevRandao": hb(h.prev_randao),
        "blockNumber": hx(h.number),
        "gasLimit": hx(h.gas_limit),
        "gasUsed": hx(h.gas_used),
        "timestamp": hx(h.timestamp),
        "extraData": hb(h.extra_data),
        "baseFeePerGas": hx(h.base_fee_per_gas or 0),
        "blockHash": hb(block.hash),
        "transactions": [hb(tx.encode_canonical())
                         for tx in block.body.transactions],
    }
    if block.body.withdrawals is not None:
        out["withdrawals"] = _withdrawals_json(block.body.withdrawals)
    if h.blob_gas_used is not None:
        out["blobGasUsed"] = hx(h.blob_gas_used)
        out["excessBlobGas"] = hx(h.excess_blob_gas)
    return out


# ---------------------------------------------------------------------------
# engine namespace
# ---------------------------------------------------------------------------

def _withdrawals_json(withdrawals) -> list[dict]:
    return [{
        "index": hx(w.index), "validatorIndex": hx(w.validator_index),
        "address": hb(w.address), "amount": hx(w.amount)}
        for w in withdrawals]


def _body_json(body) -> dict:
    return {
        "transactions": [hb(tx.encode_canonical())
                         for tx in body.transactions],
        "withdrawals": (_withdrawals_json(body.withdrawals)
                        if body.withdrawals is not None else None),
    }


class EngineApi:
    def __init__(self, node):
        self.node = node
        self.payloads: dict[str, dict] = {}
        self._payload_counter = 0

    def exchange_capabilities(self, caps):
        # per spec the response must NOT include exchangeCapabilities itself
        return [
            "engine_newPayloadV1", "engine_newPayloadV2",
            "engine_newPayloadV3", "engine_newPayloadV4",
            "engine_forkchoiceUpdatedV1", "engine_forkchoiceUpdatedV2",
            "engine_forkchoiceUpdatedV3",
            "engine_getPayloadV1", "engine_getPayloadV2",
            "engine_getPayloadV3",
            "engine_getPayloadV4", "engine_getPayloadBodiesByHashV1",
            "engine_getPayloadBodiesByRangeV1", "engine_getClientVersionV1",
        ]

    def get_client_version_v1(self, _client_version=None):
        # spec: respond with our own version info (the CL's is ignored)
        return [{"code": "EX", "name": CLIENT_NAME,
                 "version": CLIENT_VERSION, "commit": "00000000"}]

    def new_payload_v3(self, payload, blob_hashes=None,
                       parent_beacon_block_root=None,
                       execution_requests=None, *, _version=3):
        self._check_payload_fork(payload, _version)
        try:
            requests_hash = None
            if execution_requests is not None:
                from ..blockchain.blockchain import compute_requests_hash

                requests_hash = compute_requests_hash(
                    [parse_bytes(r) for r in execution_requests])
            block = payload_to_block(payload, parent_beacon_block_root,
                                     requests_hash)
        except (RpcError, KeyError, ValueError) as e:
            return {"status": INVALID, "latestValidHash": None,
                    "validationError": str(e)}
        # blob hash consistency
        want = [h for tx in block.body.transactions
                for h in tx.blob_versioned_hashes]
        got = [parse_bytes(h) for h in (blob_hashes or [])]
        if want != got:
            return {"status": INVALID, "latestValidHash": None,
                    "validationError": "blob versioned hashes mismatch"}
        store = self.node.store
        if store.get_header(block.header.parent_hash) is None:
            return {"status": SYNCING, "latestValidHash": None,
                    "validationError": None}
        if store.get_header(block.hash) is not None:
            return {"status": VALID, "latestValidHash": hb(block.hash),
                    "validationError": None}
        try:
            self.node.chain.add_block(block)
        except InvalidBlock as e:
            parent = store.get_header(block.header.parent_hash)
            return {"status": INVALID,
                    "latestValidHash": hb(parent.hash) if parent else None,
                    "validationError": str(e)}
        return {"status": VALID, "latestValidHash": hb(block.hash),
                "validationError": None}

    def new_payload_v4(self, payload, blob_hashes=None,
                       parent_beacon_block_root=None,
                       execution_requests=None):
        return self.new_payload_v3(payload, blob_hashes,
                                   parent_beacon_block_root,
                                   execution_requests, _version=4)

    # -- per-version fork gating (Engine API spec: each method version
    # serves a bounded fork range and MUST answer -38005 outside it;
    # reference: engine/payload.rs NewPayloadV1..V5 validation) -----------
    def _fork_of(self, timestamp: int) -> Fork:
        head = self.node.store.latest_number()
        return self.node.config.fork_at(head + 1, timestamp)

    def _check_payload_fork(self, payload, version: int):
        try:
            ts = parse_quantity(payload["timestamp"])
        except (KeyError, ValueError, TypeError):
            raise RpcError(-32602, "invalid payload timestamp")
        fork = self._fork_of(ts)
        if version == 1 and fork >= Fork.SHANGHAI:
            raise RpcError(-38005, "V1 payload for post-Paris fork")
        if version == 2 and fork >= Fork.CANCUN:
            raise RpcError(-38005, "V2 payload for post-Shanghai fork")
        if version == 3 and fork != Fork.CANCUN:
            raise RpcError(-38005, "V3 payload outside Cancun")
        if version == 4 and fork < Fork.PRAGUE:
            raise RpcError(-38005, "V4 payload before Prague")

    # -- legacy V1/V2 (pre-Cancun CLs) ------------------------------------
    def new_payload_v1(self, payload):
        if payload.get("withdrawals") is not None \
                or payload.get("blobGasUsed") is not None:
            raise RpcError(-32602, "V1 payload with post-Paris fields")
        return self.new_payload_v3(payload, _version=1)

    def new_payload_v2(self, payload):
        if payload.get("blobGasUsed") is not None:
            raise RpcError(-32602, "V2 payload with Cancun fields")
        return self.new_payload_v3(payload, _version=2)

    def forkchoice_updated_v3(self, state, attrs=None, *, _version=3):
        head = parse_bytes(state["headBlockHash"])
        safe = parse_bytes(state.get("safeBlockHash", "0x" + "00" * 32))
        final = parse_bytes(state.get("finalizedBlockHash",
                                      "0x" + "00" * 32))
        store = self.node.store
        if store.get_header(head) is None:
            return {"payloadStatus": {"status": SYNCING,
                                      "latestValidHash": None,
                                      "validationError": None},
                    "payloadId": None}
        try:
            # the node's reorg handler (not bare apply_fork_choice): a
            # CL-driven reorg must settle the mempool and notify
            # subscribers like any other head move
            self.node.reorg_handler.apply(
                head,
                safe if safe != b"\x00" * 32 else b"",
                final if final != b"\x00" * 32 else b"")
        except ForkChoiceError as e:
            # covers InvalidForkChoiceState (non-ancestor safe/
            # finalized) — the spec's invalidForkChoiceState error
            raise RpcError(-38002, f"invalid forkchoice state: {e}")
        payload_id = None
        if attrs:
            # spec: attribute errors must not roll back the (already
            # applied) forkchoice state; only the build is refused
            self._validate_attrs(attrs, _version)
            payload_id = self._start_payload(head, attrs)
        return {"payloadStatus": {"status": VALID,
                                  "latestValidHash": hb(head),
                                  "validationError": None},
                "payloadId": payload_id}

    def _start_payload(self, head: bytes, attrs: dict) -> str:
        parent = self.node.store.get_header(head)
        withdrawals = [
            Withdrawal(parse_quantity(w["index"]),
                       parse_quantity(w["validatorIndex"]),
                       parse_bytes(w["address"]),
                       parse_quantity(w["amount"]))
            for w in attrs.get("withdrawals", [])]
        header = create_payload_header(
            parent, self.node.config,
            timestamp=parse_quantity(attrs["timestamp"]),
            coinbase=parse_bytes(attrs["suggestedFeeRecipient"]),
            prev_randao=parse_bytes(attrs["prevRandao"]),
        )
        root = parent.state_root

        def get_nonce(sender):
            acct = self.node.store.account_state(root, sender)
            return acct.nonce if acct else 0

        txs = self.node.mempool.pending(header.base_fee_per_gas or 0,
                                        get_nonce)
        result = build_payload(
            self.node.chain, parent, header, txs, withdrawals,
            parent_beacon_block_root=parse_bytes(
                attrs.get("parentBeaconBlockRoot", "0x" + "00" * 32)),
            mempool=self.node.mempool)
        self._payload_counter += 1
        payload_id = "0x" + self._payload_counter.to_bytes(8, "big").hex()
        fees = result.fees_collected
        while len(self.payloads) >= 64:   # bound memory: evict oldest
            self.payloads.pop(next(iter(self.payloads)))
        self.payloads[payload_id] = {
            "executionPayload": block_to_payload(result.block),
            "blockValue": hx(fees),
            "blobsBundle": {"commitments": [], "proofs": [], "blobs": []},
            "shouldOverrideBuilder": False,
            "executionRequests": [],
        }
        return payload_id

    def _get_payload_checked(self, payload_id, version: int):
        payload = self.payloads.get(payload_id)
        if payload is None:
            raise RpcError(-38001, "unknown payload")
        self._check_payload_fork(payload["executionPayload"], version)
        return payload

    def get_payload_v3(self, payload_id):
        return self._get_payload_checked(payload_id, 3)

    def get_payload_v4(self, payload_id):
        return self._get_payload_checked(payload_id, 4)

    def get_payload_v1(self, payload_id):
        # V1 returns the bare ExecutionPayloadV1
        return self._get_payload_checked(payload_id, 1)["executionPayload"]

    def get_payload_v2(self, payload_id):
        full = self._get_payload_checked(payload_id, 2)
        return {"executionPayload": full["executionPayload"],
                "blockValue": full.get("blockValue", "0x0")}

    def _check_attrs_fork(self, attrs, version: int):
        try:
            ts = parse_quantity(attrs["timestamp"])
        except (KeyError, ValueError, TypeError):
            raise RpcError(-32602, "invalid payload attributes timestamp")
        fork = self._fork_of(ts)
        if version == 1 and fork >= Fork.SHANGHAI:
            raise RpcError(-38005, "V1 attributes for post-Paris fork")
        if version == 2 and fork >= Fork.CANCUN:
            raise RpcError(-38005, "V2 attributes for post-Shanghai fork")
        if version == 3 and fork < Fork.CANCUN:
            raise RpcError(-38005, "V3 attributes before Cancun")

    def _validate_attrs(self, attrs, version: int):
        """Per-version payloadAttributes validation.  Called AFTER the
        forkchoice state is applied: the Engine API spec forbids rolling
        back the forkchoiceState update when attribute validation fails."""
        if version == 1 and attrs.get("withdrawals") is not None:
            raise RpcError(-32602, "V1 attributes with withdrawals")
        if version == 2 and attrs.get("parentBeaconBlockRoot") is not None:
            raise RpcError(-32602, "V2 attributes with parentBeaconBlockRoot")
        if version == 3 and attrs.get("parentBeaconBlockRoot") is None:
            raise RpcError(
                -32602, "V3 attributes without parentBeaconBlockRoot")
        self._check_attrs_fork(attrs, version)

    def forkchoice_updated_v1(self, state, attrs=None):
        return self.forkchoice_updated_v3(state, attrs, _version=1)

    def forkchoice_updated_v2(self, state, attrs=None):
        return self.forkchoice_updated_v3(state, attrs, _version=2)

    MAX_BODIES_REQUEST = 1024  # Engine API spec limit

    def get_payload_bodies_by_hash_v1(self, hashes):
        if len(hashes) > self.MAX_BODIES_REQUEST:
            raise RpcError(-38004, "too large request")
        return [
            (_body_json(body) if (body := self.node.store.get_body(
                parse_bytes(h))) else None)
            for h in hashes
        ]

    def get_payload_bodies_by_range_v1(self, start, count):
        start_n = parse_quantity(start)
        count_n = parse_quantity(count)
        if start_n < 1 or count_n < 1:
            raise RpcError(-32602, "invalid range parameters")
        if count_n > self.MAX_BODIES_REQUEST:
            raise RpcError(-38004, "too large request")
        # spec: no trailing nulls past the latest known block
        head = self.node.store.latest_number()
        end = min(start_n + count_n - 1, head)
        out = []
        for n in range(start_n, end + 1):
            bh = self.node.store.canonical_hash(n)
            body = self.node.store.get_body(bh) if bh else None
            out.append(_body_json(body) if body else None)
        return out
