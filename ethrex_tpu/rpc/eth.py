"""eth_* / net_* / web3_* JSON-RPC handlers (parity target: the reference's
crates/networking/rpc eth namespace; SURVEY.md §2.5)."""

from __future__ import annotations

import threading

from ..primitives.transaction import Transaction
from ..evm.executor import InvalidTransaction
from ..evm.vm import EVM, BlockEnv, Message
from .serializers import (block_to_json, hb, hx, parse_bytes, parse_quantity,
                          receipt_to_json, tx_to_json)


class RpcError(Exception):
    def __init__(self, code: int, message: str, data=None):
        super().__init__(message)
        self.code = code
        self.message = message
        self.data = data


CLIENT_NAME = "ethrex-tpu"
CLIENT_VERSION = "0.1.0"


class EthApi:
    """Implements the eth namespace against a Node (node.py)."""

    FILTER_TTL = 300.0   # seconds since last poll before a filter expires

    def __init__(self, node):
        self.node = node
        # id -> filter record (parity: the reference's rpc/eth/filter.rs
        # in-memory FilterStore with last-poll TTL cleanup)
        self._filters: dict = {}
        self._filter_lock = threading.Lock()
        self._filter_counter = 0
        node.mempool.on_add.append(self._on_pending_tx)

    def _on_pending_tx(self, tx_hash: bytes):
        """Mempool arrival hook: queue the hash into every live
        pending-transaction filter so a tx mined between two polls is
        still reported."""
        with self._filter_lock:
            for f in self._filters.values():
                if f["kind"] == "pendingTransactions":
                    f["queue"].append(tx_hash)

    # ---------------- helpers ----------------
    def _resolve_block(self, tag) -> "Block":
        store = self.node.store
        if tag is None:
            tag = "latest"
        if isinstance(tag, dict):
            if "blockHash" in tag:
                blk = store.get_block(parse_bytes(tag["blockHash"]))
            else:
                blk = store.get_canonical_block(
                    parse_quantity(tag["blockNumber"]))
        elif tag in ("latest", "pending", "safe", "finalized"):
            key = {"latest": "head", "pending": "head",
                   "safe": "safe", "finalized": "finalized"}[tag]
            blk = store.get_block(store.meta[key])
        elif tag == "earliest":
            blk = store.get_canonical_block(0)
        else:
            blk = store.get_canonical_block(parse_quantity(tag))
        if blk is None:
            raise RpcError(-38001, "unknown block")
        return blk

    def _state_root(self, tag) -> bytes:
        return self._resolve_block(tag).header.state_root

    # ---------------- basic ----------------
    def chain_id(self):
        return hx(self.node.config.chain_id)

    def block_number(self):
        return hx(self.node.store.latest_number())

    def get_balance(self, address, tag="latest"):
        acct = self.node.store.account_state(
            self._state_root(tag), parse_bytes(address))
        return hx(acct.balance if acct else 0)

    def get_transaction_count(self, address, tag="latest"):
        if tag == "pending":
            n = self.node.pending_nonce(parse_bytes(address))
            return hx(n)
        acct = self.node.store.account_state(
            self._state_root(tag), parse_bytes(address))
        return hx(acct.nonce if acct else 0)

    def get_code(self, address, tag="latest"):
        acct = self.node.store.account_state(
            self._state_root(tag), parse_bytes(address))
        if acct is None:
            return "0x"
        return hb(self.node.store.code.get(acct.code_hash, b""))

    def get_storage_at(self, address, slot, tag="latest"):
        value = self.node.store.storage_at(
            self._state_root(tag), parse_bytes(address),
            parse_quantity(slot))
        return hb(value.to_bytes(32, "big"))

    def gas_price(self):
        head = self.node.store.head_header()
        return hx((head.base_fee_per_gas or 0) + 10**9)

    def max_priority_fee_per_gas(self):
        return hx(10**9)

    def syncing(self):
        return False

    def blob_base_fee(self):
        from ..evm import gas as G

        head = self.node.store.head_header()
        _, _, fraction = self.node.config.blob_params_at(head.timestamp)
        return hx(G.blob_base_fee(head.excess_blob_gas or 0, fraction))

    def block_tx_count(self, tag):
        try:
            return hx(len(self._resolve_block(tag).body.transactions))
        except RpcError:
            return None

    def block_tx_count_by_hash(self, block_hash):
        blk = self.node.store.get_block(parse_bytes(block_hash))
        return hx(len(blk.body.transactions)) if blk else None

    def tx_by_block_and_index(self, tag, index):
        try:
            blk = self._resolve_block(tag)
        except RpcError:
            return None  # unknown block -> null (spec/geth behavior)
        i = parse_quantity(index)
        if i < 0 or i >= len(blk.body.transactions):
            return None
        return tx_to_json(blk.body.transactions[i], blk.hash,
                          blk.header.number, i)

    # ---------------- blocks / txs ----------------
    def get_block_by_number(self, tag, full=False):
        try:
            return block_to_json(self._resolve_block(tag), full)
        except RpcError:
            return None

    def get_block_by_hash(self, block_hash, full=False):
        blk = self.node.store.get_block(parse_bytes(block_hash))
        return block_to_json(blk, full) if blk else None

    def get_transaction_by_hash(self, tx_hash):
        store = self.node.store
        h = parse_bytes(tx_hash)
        # canonical-verified lookup: a txloc pointing at an orphaned
        # block (reorg race, stale index) must never be served as an
        # inclusion — fall back to the pool (a re-injected tx is
        # pending again) or null (docs/CHAIN_RESILIENCE.md)
        loc = store.canonical_tx_location(h)
        if loc is None:
            tx = self.node.mempool.get_transaction(h)
            return tx_to_json(tx) if tx else None
        blk = store.get_block(loc[0])
        return tx_to_json(blk.body.transactions[loc[1]], loc[0],
                          blk.header.number, loc[1])

    def get_transaction_receipt(self, tx_hash):
        store = self.node.store
        # same canonical-verified lookup as get_transaction_by_hash: an
        # orphaned inclusion's receipt no longer exists on the chain
        loc = store.canonical_tx_location(parse_bytes(tx_hash))
        if loc is None:
            return None
        blk = store.get_block(loc[0])
        receipts = store.get_receipts(loc[0])
        idx = loc[1]
        rec = receipts[idx]
        tx = blk.body.transactions[idx]
        prev = receipts[idx - 1].cumulative_gas_used if idx else 0
        log_base = sum(len(r.logs) for r in receipts[:idx])
        eff = tx.effective_gas_price(blk.header.base_fee_per_gas or 0) or 0
        return receipt_to_json(rec, tx, blk, idx, eff, prev, log_base)

    def get_block_receipts(self, tag):
        blk = self._resolve_block(tag)
        receipts = self.node.store.get_receipts(blk.hash) or []
        out = []
        prev = 0
        log_base = 0
        for i, (rec, tx) in enumerate(zip(receipts, blk.body.transactions)):
            eff = tx.effective_gas_price(blk.header.base_fee_per_gas or 0) or 0
            out.append(receipt_to_json(rec, tx, blk, i, eff, prev, log_base))
            prev = rec.cumulative_gas_used
            log_base += len(rec.logs)
        return out

    def get_logs(self, flt):
        from_b = self._resolve_block(flt.get("fromBlock", "latest"))
        to_b = self._resolve_block(flt.get("toBlock", "latest"))
        want_addr = flt.get("address")
        if isinstance(want_addr, str):
            want_addr = [want_addr]
        want_addr = ({parse_bytes(a) for a in want_addr}
                     if want_addr else None)
        topics = flt.get("topics") or []
        out = []
        store = self.node.store
        for num in range(from_b.header.number, to_b.header.number + 1):
            blk = store.get_canonical_block(num)
            if blk is None:
                continue
            receipts = store.get_receipts(blk.hash) or []
            log_base = 0
            for i, (rec, tx) in enumerate(
                    zip(receipts, blk.body.transactions)):
                for j, log in enumerate(rec.logs):
                    if want_addr and log.address not in want_addr:
                        continue
                    if not _topics_match(log.topics, topics):
                        continue
                    out.append({
                        "address": hb(log.address),
                        "topics": [hb(t) for t in log.topics],
                        "data": hb(log.data),
                        "blockHash": hb(blk.hash),
                        "blockNumber": hx(num),
                        "transactionHash": hb(tx.hash),
                        "transactionIndex": hx(i),
                        "logIndex": hx(log_base + j),
                        "removed": False,
                    })
                log_base += len(rec.logs)
        return out

    # ---------------- filters (polling API) ----------------
    def _expire_locked(self, now: float):
        self._filters = {k: v for k, v in self._filters.items()
                         if now - v["polled"] < self.FILTER_TTL}

    def _install_filter(self, kind: str, criteria=None) -> str:
        import os as _os
        import time as _time
        criteria = criteria or {}
        # resolve the filter's own range once, at install time
        start = self._resolve_block(
            criteria.get("fromBlock", "latest")).header.number
        to_tag = criteria.get("toBlock", "latest")
        to_limit = (None if to_tag in ("latest", "pending", "safe",
                                       "finalized", "earliest", None)
                    else self._resolve_block(to_tag).header.number)
        with self._filter_lock:
            now = _time.monotonic()
            self._expire_locked(now)
            self._filter_counter += 1
            fid = hx(int.from_bytes(_os.urandom(4), "big") * 2**32
                     + self._filter_counter)
            self._filters[fid] = {
                "kind": kind, "criteria": criteria,
                "last_block": (start - 1 if kind == "log"
                               else self.node.store.latest_number()),
                "to_limit": to_limit,
                "queue": [],
                "polled": now,
            }
            return fid

    def new_filter(self, flt):
        return self._install_filter("log", flt)

    def new_block_filter(self):
        return self._install_filter("block")

    def new_pending_transaction_filter(self):
        return self._install_filter("pendingTransactions")

    def uninstall_filter(self, fid) -> bool:
        with self._filter_lock:
            return self._filters.pop(fid, None) is not None

    def _poll_locked(self, fid):
        """Look up + TTL-check + touch a filter; caller holds the lock."""
        import time as _time
        now = _time.monotonic()
        self._expire_locked(now)
        f = self._filters.get(fid)
        if f is None:
            raise RpcError(-32000, "filter not found")
        f["polled"] = now
        return f

    def get_filter_changes(self, fid):
        with self._filter_lock:
            f = self._poll_locked(fid)
            head = self.node.store.latest_number()
            if f["kind"] == "block":
                out = []
                for n in range(f["last_block"] + 1, head + 1):
                    bh = self.node.store.canonical_hash(n)
                    if bh:
                        out.append(hb(bh))
                f["last_block"] = head
                return out
            if f["kind"] == "pendingTransactions":
                out = [hb(h) for h in f["queue"]]
                f["queue"] = []
                return out
            # log filter: new matches in [last_block+1, min(head, toBlock)]
            hi = head if f["to_limit"] is None else min(head, f["to_limit"])
            lo = f["last_block"] + 1
            if lo > hi:
                return []
            crit = dict(f["criteria"])
            crit["fromBlock"] = hx(lo)
            crit["toBlock"] = hx(hi)
            f["last_block"] = hi
        return self.get_logs(crit)

    def get_filter_logs(self, fid):
        with self._filter_lock:
            f = self._poll_locked(fid)
            if f["kind"] != "log":
                raise RpcError(-32000, "not a log filter")
            crit = dict(f["criteria"])
        return self.get_logs(crit)

    # ---------------- execution ----------------
    def _call_msg(self, call, tag):
        blk = self._resolve_block(tag)
        header = blk.header
        state = self.node.store.state_db(header.state_root)
        state.begin_tx()
        env = BlockEnv(
            number=header.number, coinbase=header.coinbase,
            timestamp=header.timestamp, gas_limit=header.gas_limit,
            prev_randao=header.prev_randao,
            base_fee=header.base_fee_per_gas or 0,
            excess_blob_gas=header.excess_blob_gas or 0,
        )
        sender = parse_bytes(call.get("from", "0x" + "00" * 20))
        to = parse_bytes(call["to"]) if call.get("to") else b""
        gas = parse_quantity(call.get("gas", hex(header.gas_limit)))
        value = parse_quantity(call.get("value", "0x0"))
        data = parse_bytes(call.get("data") or call.get("input") or "0x")
        evm = EVM(state, env, self.node.config, origin=sender)
        if to:
            code, code_src = evm.resolve_code(to)
            msg = Message(caller=sender, to=to, code_address=code_src,
                          value=value, data=data, gas=gas, code=code)
            from ..evm import precompiles
            if to in precompiles.PRECOMPILES:
                msg.code_address = to
        else:
            msg = Message(caller=sender, to=b"", code_address=b"",
                          value=value, data=b"", gas=gas, is_create=True,
                          code=data)
        return evm.execute_message(msg)

    def call(self, call, tag="latest"):
        ok, _, output = self._call_msg(call, tag)
        if not ok:
            raise RpcError(3, "execution reverted", hb(output))
        return hb(output)

    def estimate_gas(self, call, tag="latest"):
        # binary search over gas like the reference's estimate flow
        blk = self._resolve_block(tag)
        hi = parse_quantity(call.get("gas", hex(blk.header.gas_limit)))
        lo = 0  # frame-level gas; the tx intrinsic cost is added at the end
        call = dict(call)

        def ok_with(gas):
            call["gas"] = hex(gas)
            ok, _, _ = self._call_msg(call, tag)
            return ok

        if not ok_with(hi):
            raise RpcError(3, "execution reverted")
        while lo + 1 < hi:
            mid = (lo + hi) // 2
            if ok_with(mid):
                hi = mid
            else:
                lo = mid
        # add the intrinsic cost the message path doesn't charge
        data = parse_bytes(call.get("data") or call.get("input") or "0x")
        from ..evm import gas as G
        intrinsic = G.TX_BASE + G.tx_data_cost(data)[0]
        return hx(hi + intrinsic)

    def send_raw_transaction(self, raw):
        from ..primitives.rlp import RLPError

        try:
            tx = Transaction.decode_canonical(parse_bytes(raw))
        except (RLPError, ValueError) as e:
            raise RpcError(-32602, f"invalid raw transaction: {e}")
        try:
            self.node.submit_transaction(tx)
        except InvalidTransaction as e:
            # typed mempool rejections carry their machine-readable
            # reason as structured error data: load generators account
            # them per reason ("rejections" section) instead of folding
            # admission-control pushback into a generic error rate
            reason = getattr(e, "reason", None)
            if reason:
                raise RpcError(-32000, str(e),
                               {"rejected": True, "reason": reason})
            raise RpcError(-32000, str(e))
        return hb(tx.hash)

    def get_proof(self, address, slots, tag="latest"):
        """eth_getProof: account + storage Merkle proofs."""
        from ..crypto.keccak import keccak256
        from ..primitives import rlp as _rlp
        from ..primitives.account import AccountState, EMPTY_TRIE_ROOT
        from ..trie.trie import Trie

        store = self.node.store
        root = self._state_root(tag)
        addr = parse_bytes(address)
        trie = Trie.from_nodes(root, store.nodes, share=True)
        key = keccak256(addr)
        account_proof = [hb(n) for n in trie.get_proof(key)]
        raw = trie.get(key)
        acct = AccountState.decode(raw) if raw else AccountState()
        storage_proofs = []
        st = None
        if acct.storage_root != EMPTY_TRIE_ROOT:
            st = Trie.from_nodes(acct.storage_root, store.nodes, share=True)
        for slot in slots or []:
            slot_i = parse_quantity(slot)
            skey = keccak256(slot_i.to_bytes(32, "big"))
            if st is None:
                storage_proofs.append(
                    {"key": hx(slot_i), "value": "0x0", "proof": []})
                continue
            sraw = st.get(skey)
            value = _rlp.decode_int(_rlp.decode(sraw)) if sraw else 0
            storage_proofs.append({
                "key": hx(slot_i), "value": hx(value),
                "proof": [hb(n) for n in st.get_proof(skey)]})
        return {
            "address": hb(addr),
            "accountProof": account_proof,
            "balance": hx(acct.balance),
            "nonce": hx(acct.nonce),
            "codeHash": hb(acct.code_hash),
            "storageHash": hb(acct.storage_root),
            "storageProof": storage_proofs,
        }

    def debug_execution_witness(self, from_tag, to_tag=None):
        """debug_executionWitness: witness for a canonical block range
        (the reference's replay/prover entry point)."""
        from ..guest.witness import generate_witness

        MAX_RANGE = 128  # bound the synchronous re-execution work per call
        from_b = self._resolve_block(from_tag)
        to_b = self._resolve_block(to_tag if to_tag is not None else from_tag)
        first, last = from_b.header.number, to_b.header.number
        if first == 0:
            raise RpcError(-32602, "cannot generate a witness for genesis")
        if last < first:
            raise RpcError(-32602, "invalid range: toBlock before fromBlock")
        if last - first + 1 > MAX_RANGE:
            raise RpcError(-32602, f"range exceeds {MAX_RANGE} blocks")
        store = self.node.store
        # only canonical blocks: a side-chain hash tag must not silently
        # resolve to the canonical block at the same height
        for b in (from_b, to_b):
            if store.canonical_hash(b.header.number) != b.hash:
                raise RpcError(-32602, "block is not canonical")
        blocks = [store.get_canonical_block(n)
                  for n in range(first, last + 1)]
        if any(b is None for b in blocks):
            raise RpcError(-38001, "unknown block in range")
        witness = generate_witness(self.node.chain, blocks)
        return witness.to_json()

    def debug_trace_transaction(self, tx_hash, opts=None):
        """debug_traceTransaction: geth-default structLogs when no tracer
        is named, or the callTracer (parity: rpc/tracing.rs +
        levm opcode_tracer.rs)."""
        from ..evm.executor import execute_tx
        from ..evm.tracing import CallTracer, StructLogTracer
        from ..evm.vm import BlockEnv

        opts = opts or {}
        tracer_name = opts.get("tracer", "structLogs")
        if tracer_name not in ("callTracer", "structLogs"):
            raise RpcError(-32602, f"unsupported tracer {tracer_name!r}")
        store = self.node.store
        # canonical-verified like get_transaction_by_hash: tracing an
        # orphaned inclusion would replay state that is no longer chain
        loc = store.canonical_tx_location(parse_bytes(tx_hash))
        if loc is None:
            raise RpcError(-32602, "transaction not found")
        blk = store.get_block(loc[0])
        header = blk.header
        parent = store.get_header(header.parent_hash)
        state = store.state_db(parent.state_root)
        env = BlockEnv(
            number=header.number, coinbase=header.coinbase,
            timestamp=header.timestamp, gas_limit=header.gas_limit,
            prev_randao=header.prev_randao,
            base_fee=header.base_fee_per_gas or 0,
            excess_blob_gas=header.excess_blob_gas or 0,
            parent_beacon_block_root=header.parent_beacon_block_root
            or b"\x00" * 32,
        )
        fork = self.node.config.fork_at(header.number, header.timestamp)
        self.node.chain._pre_tx_system_ops(state, env, header, fork)
        # replay preceding txs untraced, then trace the target
        for tx in blk.body.transactions[:loc[1]]:
            execute_tx(tx, state, env, self.node.config)
        if tracer_name == "callTracer":
            tracer = CallTracer()
        else:
            # geth TraceConfig inlines the struct-logger options at the top
            # level (disableStack/limit); tracerConfig is read as a fallback
            # for callers that nest them
            cfg = {**(opts.get("tracerConfig") or {}), **opts}
            tracer = StructLogTracer(
                with_stack=not cfg.get("disableStack", False),
                max_logs=int(cfg.get("limit", 100_000)))
        res = execute_tx(blk.body.transactions[loc[1]], state, env,
                         self.node.config, tracer=tracer)
        out = tracer.result()
        if tracer_name == "structLogs":
            out = {"gas": res.gas_used, "failed": not res.success,
                   "returnValue": res.output.hex(), **out}
        return out

    def fee_history(self, count, newest, percentiles=None):
        count = parse_quantity(count)
        newest_b = self._resolve_block(newest)
        base_fees = []
        ratios = []
        start = max(0, newest_b.header.number - count + 1)
        for num in range(start, newest_b.header.number + 1):
            blk = self.node.store.get_canonical_block(num)
            base_fees.append(hx(blk.header.base_fee_per_gas or 0))
            ratios.append(blk.header.gas_used / blk.header.gas_limit
                          if blk.header.gas_limit else 0.0)
        from ..blockchain.blockchain import next_base_fee
        base_fees.append(hx(next_base_fee(newest_b.header)))
        return {
            "oldestBlock": hx(start),
            "baseFeePerGas": base_fees,
            "gasUsedRatio": ratios,
            "reward": [[hx(10**9)] * len(percentiles or [])
                       for _ in range(len(ratios))],
        }


def _topics_match(log_topics, want) -> bool:
    for i, t in enumerate(want):
        if t is None:
            continue
        if i >= len(log_topics):
            return False
        options = t if isinstance(t, list) else [t]
        if "0x" + log_topics[i].hex() not in options:
            return False
    return True
