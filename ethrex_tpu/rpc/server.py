"""JSON-RPC HTTP server + method routing (parity target: the reference's
crates/networking/rpc/rpc.rs start_api).

Transport: one asyncio event loop (rpc/aio.LoopThread) accepts
connections, parses pipelined keep-alive HTTP/1.1, and dispatches
JSON-RPC — single requests and spec batch arrays — onto a BOUNDED
thread-pool executor.  The stage split follows SEDA (Welsh et al.,
"SEDA: An Architecture for Well-Conditioned, Scalable Internet
Services", SOSP 2001; PAPERS.md): the loop stage only parses, admits
and writes; the executor stage runs the blocking store/EVM handler
bodies.  Admission control (utils/overload.py) runs ON THE LOOP before
a request may take an executor slot, so a shed under saturation costs
microseconds — the executor can be pinned full of heavy work and the
typed busy answer still goes out inside the <10ms shed budget
(docs/OVERLOAD.md).

Responses on one connection are written strictly in request order by a
per-connection writer coroutine draining an ordered queue of response
tasks, so HTTP/1.1 pipelining is safe while handlers complete out of
order on the executor.  Batch arrays are dispatched concurrently
(asyncio.gather), reassembled in order, capped (ETHREX_RPC_MAX_BATCH)
and counted (rpc_batch_requests_total)."""

from __future__ import annotations

import asyncio
import json
import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from ..utils.faults import inject
from ..utils.metrics import (METRICS, observe_rpc_queue_wait,
                             observe_rpc_request, record_rpc_accept,
                             record_rpc_backlog, record_rpc_batch,
                             record_rpc_bytes, record_rpc_eof,
                             record_rpc_executor_workers,
                             record_rpc_inflight,
                             record_rpc_method_inflight, record_rpc_reset,
                             record_rpc_slow_request)
from ..utils.overload import SERVER_BUSY_CODE, OverloadController
from ..utils.tracing import TRACER, trace_context

from .aio import LoopThread
from .eth import (CLIENT_NAME, CLIENT_VERSION, EthApi,
                  RpcError)  # noqa: F401 (RpcError used below)

LOG = logging.getLogger("ethrex.rpc")

# Requests slower than this emit one structured log line (with the trace
# ID) and bump rpc_slow_requests_total.  Env override so operators can
# tighten it without a restart script change.
SLOW_REQUEST_SECONDS = float(os.environ.get("ETHREX_RPC_SLOW_SECONDS",
                                            "1.0"))
DEFAULT_BACKLOG = 128

# Execution-stage pool bound: blocking handler bodies (store reads, EVM
# calls, signature recovery) run here so they never stall the event
# loop.  Admission control caps per-class concurrency separately and
# FIRST, on the loop — the executor bound is the hard backstop.
EXECUTOR_WORKERS = int(os.environ.get("ETHREX_RPC_EXECUTOR_WORKERS",
                                      "16"))
# JSON-RPC batch array cap: one array must not amplify into unbounded
# concurrent dispatch.  Oversized (or empty) batches are answered with
# a typed -32600, never a closed connection.
MAX_BATCH = int(os.environ.get("ETHREX_RPC_MAX_BATCH", "64"))
# Single request body cap: a larger Content-Length is drained (framing
# stays in sync) and answered with a typed -32600 on a live connection.
MAX_BODY_BYTES = int(os.environ.get("ETHREX_RPC_MAX_BODY",
                                    str(8 * 1024 * 1024)))
# StreamReader buffer limit — bounds readuntil() header scans.
_READER_LIMIT = 256 * 1024

_REASONS = {200: b"OK", 400: b"Bad Request", 401: b"Unauthorized",
            405: b"Method Not Allowed",
            431: b"Request Header Fields Too Large"}


def _http_response(status: int, body: bytes,
                   ctype: bytes = b"application/json") -> bytes:
    return (b"HTTP/1.1 %d %s\r\n"
            b"Content-Type: %s\r\n"
            b"Content-Length: %d\r\n"
            b"\r\n" % (status, _REASONS.get(status, b""), ctype,
                       len(body))) + body


class _Admitted:
    """An admitted request: everything _execute() needs, produced by
    _admit() on the event loop (or on the caller's thread for the
    direct handle() path) BEFORE any executor slot is taken."""

    __slots__ = ("rid", "method", "params", "fn", "decision")

    def __init__(self, rid, method, params, fn, decision):
        self.rid = rid
        self.method = method
        self.params = params
        self.fn = fn
        self.decision = decision


class _ListenerShim:
    """Compatibility handle kept at `server._httpd`: the pre-asyncio
    transport exposed the stdlib ThreadingHTTPServer there, and
    operational surfaces use `.request_queue_size` for the configured
    listen backlog and `.shutdown()` to stop the server."""

    def __init__(self, server: "RpcServer", request_queue_size: int):
        self._server = server
        self.request_queue_size = request_queue_size

    def shutdown(self):
        self._server.stop()

    def server_close(self):
        pass


class _HttpConn:
    """One keep-alive HTTP connection on the event loop.

    The reader coroutine parses pipelined requests and creates one
    response task per request; the writer coroutine drains an ORDERED
    queue of those tasks, so responses go out in request order no
    matter how the handlers interleave on the executor."""

    __slots__ = ("server", "reader", "writer", "queue", "accepted_at",
                 "reader_task", "writer_task")

    def __init__(self, server: "RpcServer", reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter):
        self.server = server
        self.reader = reader
        self.writer = writer
        self.queue: asyncio.Queue = asyncio.Queue()
        self.accepted_at = time.monotonic()
        self.reader_task: asyncio.Task | None = None
        self.writer_task: asyncio.Task | None = None

    # -- reader --------------------------------------------------------
    async def read_loop(self):
        # queue-wait signal: accept (connection_made) → first read
        # attempt.  In an event-driven server the accept backlog shows
        # up as loop-scheduling delay, so this is the asyncio analog of
        # the old accept-thread→handler-thread handoff wait.  Client
        # idle time on a pre-opened keep-alive connection is NOT queue
        # wait — charging it would spike the shed ladder on healthy
        # persistent clients (connection pools open sockets early).
        wait = time.monotonic() - self.accepted_at
        observe_rpc_queue_wait(wait)
        self.server.overload.note_queue_wait(wait)
        while True:
            try:
                head = await self.reader.readuntil(b"\r\n\r\n")
            except asyncio.IncompleteReadError as exc:
                if exc.partial:
                    # peer closed mid-headers; a clean EOF between
                    # requests is just the client hanging up
                    record_rpc_eof()
                return
            except (asyncio.LimitOverrunError, ValueError):
                self.queue.put_nowait(_http_response(
                    431, b"header block too large", b"text/plain"))
                return
            except (ConnectionError, OSError):
                record_rpc_reset()
                return
            request_line, _, header_block = head.partition(b"\r\n")
            parts = request_line.split()
            if len(parts) < 2:
                self.queue.put_nowait(_http_response(
                    400, b"bad request line", b"text/plain"))
                return
            headers: dict[str, str] = {}
            for line in header_block.split(b"\r\n"):
                if b":" in line:
                    key, value = line.split(b":", 1)
                    headers[key.strip().lower().decode("latin-1")] = \
                        value.strip().decode("latin-1")
            close_after = "close" in headers.get("connection", "").lower()
            try:
                length = int(headers.get("content-length", "0"))
            except ValueError:
                self.queue.put_nowait(_http_response(
                    400, b"bad content-length", b"text/plain"))
                return
            if parts[0].upper() != b"POST":
                if not await self._discard(length):
                    return
                self.queue.put_nowait(_http_response(
                    405, b"POST only", b"text/plain"))
                if close_after:
                    return
                continue
            if length > MAX_BODY_BYTES:
                if not await self._discard(length):
                    return
                self.queue.put_nowait(_http_response(200, json.dumps(
                    _err(None, -32600,
                         "request body too large")).encode()))
                if close_after:
                    return
                continue
            try:
                body = await self.reader.readexactly(length)
            except asyncio.IncompleteReadError:
                record_rpc_eof()
                return
            except (ConnectionError, OSError):
                record_rpc_reset()
                return
            server = self.server
            if server.jwt_secret is not None and not server._authorized(
                    headers.get("authorization", "")):
                self.queue.put_nowait(_http_response(
                    401, b"unauthorized", b"text/plain"))
                if close_after:
                    return
                continue
            # each request's queue age starts at parse time: deadline
            # shedding should see loop-dispatch delay (the gap between
            # this stamp and _admit running), never client idle time
            # on a keep-alive connection
            task = asyncio.ensure_future(
                server._respond(body, time.monotonic()))
            server._pending.add(task)
            task.add_done_callback(server._pending.discard)
            self.queue.put_nowait(task)
            if close_after:
                return

    async def _discard(self, length: int) -> bool:
        """Drain `length` body bytes without buffering them."""
        try:
            while length > 0:
                chunk = await self.reader.read(min(length, 65536))
                if not chunk:
                    record_rpc_eof()
                    return False
                length -= len(chunk)
        except (ConnectionError, OSError):
            record_rpc_reset()
            return False
        return True

    # -- writer --------------------------------------------------------
    async def write_loop(self):
        try:
            while True:
                item = await self.queue.get()
                if item is None:
                    return
                if isinstance(item, bytes):
                    payload = item
                else:
                    payload = _http_response(200, await item)
                self.writer.write(payload)
                await self.writer.drain()
        except (ConnectionError, OSError):
            # the client hung up mid-response — backlog-pressure
            # signal, never a server traceback
            record_rpc_reset()
        except asyncio.CancelledError:
            pass
        finally:
            self.server._conns.discard(self)
            try:
                self.writer.close()
            except Exception:  # noqa: BLE001 — transport teardown
                pass

    def abort(self):
        try:
            transport = self.writer.transport
            if transport is not None:
                transport.abort()
        except Exception:  # noqa: BLE001 — already closed
            pass


class RpcServer:
    def __init__(self, node, host: str = "127.0.0.1", port: int = 8545,
                 jwt_secret: bytes | None = None, engine: bool = False,
                 admin: bool = False, backlog: int | None = None,
                 overload: OverloadController | None = None,
                 executor_workers: int | None = None,
                 max_batch: int | None = None):
        self.node = node
        self.eth = EthApi(node)
        self.host = host
        self.port = port
        self.jwt_secret = jwt_secret
        self.admin_enabled = admin
        self.backlog = backlog
        self.executor_workers = int(executor_workers) \
            if executor_workers is not None else EXECUTOR_WORKERS
        self.max_batch = int(max_batch) if max_batch is not None \
            else MAX_BATCH
        # admission control (docs/OVERLOAD.md): mempool utilization
        # feeds the shed ladder so tx submission sheds before the pool
        # starts thrashing its eviction queues
        self.overload = overload if overload is not None else \
            OverloadController(mempool_probe=lambda: _mempool_util(node))
        # expose the controller for health/snapshot surfaces that only
        # hold the node (last-attached server wins, single-node truth)
        node.rpc_overload = self.overload
        self._httpd: _ListenerShim | None = None
        self._loop_thread: LoopThread | None = None
        self._aio_server: asyncio.AbstractServer | None = None
        self._conns: set[_HttpConn] = set()
        self._pending: set[asyncio.Future] = set()
        self._executor: ThreadPoolExecutor | None = None
        self._exec_lock = threading.Lock()
        self._inflight_lock = threading.Lock()
        self._inflight = 0
        self._inflight_by_method: dict[str, int] = {}
        self.methods = self._build_methods()
        if engine:
            from .engine import EngineApi

            api = EngineApi(node)
            self.engine_api = api
            self.methods.update({
                "engine_exchangeCapabilities": api.exchange_capabilities,
                "engine_newPayloadV1": api.new_payload_v1,
                "engine_newPayloadV2": api.new_payload_v2,
                "engine_newPayloadV3": api.new_payload_v3,
                "engine_newPayloadV4": api.new_payload_v4,
                "engine_forkchoiceUpdatedV1": api.forkchoice_updated_v1,
                "engine_forkchoiceUpdatedV2": api.forkchoice_updated_v2,
                "engine_forkchoiceUpdatedV3": api.forkchoice_updated_v3,
                "engine_getPayloadV1": api.get_payload_v1,
                "engine_getPayloadV2": api.get_payload_v2,
                "engine_getPayloadV3": api.get_payload_v3,
                "engine_getPayloadV4": api.get_payload_v4,
                "engine_getPayloadBodiesByHashV1":
                    api.get_payload_bodies_by_hash_v1,
                "engine_getPayloadBodiesByRangeV1":
                    api.get_payload_bodies_by_range_v1,
                "engine_getClientVersionV1": api.get_client_version_v1,
            })

    def _build_methods(self):
        e = self.eth
        node = self.node
        return {
            "eth_chainId": lambda: e.chain_id(),
            "eth_blockNumber": lambda: e.block_number(),
            "eth_getBalance": e.get_balance,
            "eth_getTransactionCount": e.get_transaction_count,
            "eth_getCode": e.get_code,
            "eth_getStorageAt": e.get_storage_at,
            "eth_gasPrice": lambda: e.gas_price(),
            "eth_maxPriorityFeePerGas": lambda: e.max_priority_fee_per_gas(),
            "eth_syncing": lambda: e.syncing(),
            "eth_getBlockByNumber": e.get_block_by_number,
            "eth_getBlockByHash": e.get_block_by_hash,
            "eth_getTransactionByHash": e.get_transaction_by_hash,
            "eth_getTransactionReceipt": e.get_transaction_receipt,
            "eth_getBlockReceipts": e.get_block_receipts,
            "eth_getLogs": e.get_logs,
            "eth_newFilter": e.new_filter,
            "eth_newBlockFilter": lambda: e.new_block_filter(),
            "eth_newPendingTransactionFilter":
                lambda: e.new_pending_transaction_filter(),
            "eth_getFilterChanges": e.get_filter_changes,
            "eth_getFilterLogs": e.get_filter_logs,
            "eth_uninstallFilter": e.uninstall_filter,
            "eth_call": e.call,
            "eth_estimateGas": e.estimate_gas,
            "eth_sendRawTransaction": e.send_raw_transaction,
            "eth_feeHistory": e.fee_history,
            "eth_getProof": e.get_proof,
            "debug_executionWitness": e.debug_execution_witness,
            "debug_traceTransaction": e.debug_trace_transaction,
            "net_version": lambda: str(node.config.chain_id),
            "net_listening": lambda: True,
            "net_peerCount": lambda: hex(_peer_count(node)),
            "web3_clientVersion":
                lambda: f"{CLIENT_NAME}/{CLIENT_VERSION}",
            "web3_sha3": _sha3,
            "eth_blobBaseFee": lambda: e.blob_base_fee(),
            "eth_getBlockTransactionCountByNumber": e.block_tx_count,
            "eth_getBlockTransactionCountByHash":
                e.block_tx_count_by_hash,
            "eth_getTransactionByBlockNumberAndIndex":
                e.tx_by_block_and_index,
            "txpool_content": lambda: _txpool_content(node),
            "txpool_status": lambda: _txpool_status(node),
            "admin_nodeInfo": lambda: _admin_node_info(node),
            "admin_peers": lambda: _admin_peers(node),
            # post-merge constants / wallet compatibility
            "eth_accounts": lambda: [],
            "eth_mining": lambda: False,
            "eth_hashrate": lambda: "0x0",
            # uncles are always empty post-merge, but unknown blocks
            # must still answer null (matching block_tx_count's convention)
            "eth_getUncleCountByBlockHash":
                lambda h: None if e.block_tx_count_by_hash(h) is None
                else "0x0",
            "eth_getUncleCountByBlockNumber":
                lambda n: None if e.block_tx_count(n) is None else "0x0",
            "eth_getUncleByBlockHashAndIndex": lambda h, i: None,
            "eth_getUncleByBlockNumberAndIndex": lambda n, i: None,
            "ethrex_produceBlock": lambda: _produce(node),
            # L2 namespace (reference: crates/l2/networking/rpc)
            "ethrex_latestBatch": lambda: _latest_batch(node),
            "ethrex_getBatchByNumber": lambda n: _get_batch(node, n),
            "ethrex_health": lambda: _health(node),
            "ethrex_ready": lambda: _ready(node),
            "ethrex_getL1MessageProof":
                lambda h: _l1_message_proof(node, h),
            "ethrex_batchNumberByBlock":
                lambda n: _batch_by_block(node, n),
            "ethrex_adminStopCommitter":
                lambda: _admin_committer(self, node, False),
            "ethrex_adminStartCommitter":
                lambda *a: _admin_committer(self, node, True,
                                            *(a[:1] or (0,))),
            "ethrex_adminSetStopAtBatch":
                lambda n=None: _admin_stop_at(self, node, n),
            # tracing namespace: serve the in-process trace ring buffer
            "ethrex_trace_recentTraces":
                lambda limit=None: TRACER.recent(_trace_limit(limit)),
            "ethrex_trace_slowest":
                lambda limit=None: TRACER.slowest(_trace_limit(limit)),
            # merged-trace analysis (docs/OBSERVABILITY.md "Distributed
            # tracing"): blocking-chain attribution and Perfetto export;
            # both degrade to found=False stubs on nodes with nothing in
            # the ring (L1-only / pre-tracing peers)
            "ethrex_trace_criticalPath":
                lambda tid=None: _trace_critical_path(tid),
            "ethrex_trace_export": lambda tid=None: _trace_export(tid),
            # SLO/alert engine + flight recorder (docs/OBSERVABILITY.md)
            "ethrex_alerts": lambda: _alerts(node),
            "ethrex_debug_snapshot": lambda: _debug_snapshot(node),
            # continuous profiler + roofline (docs/PERFORMANCE.md)
            "ethrex_perf": lambda: _perf(node),
            # chain-path X-ray (docs/OBSERVABILITY.md "Chain-path
            # telemetry"): stage queues, sampled tx lifecycles and the
            # bottleneck explainer; degrades to an idle stub on L1-only
            # nodes that never produce blocks
            "ethrex_chainPath": lambda: _chain_path(node),
        }

    def _track_inflight(self, method: str, delta: int):
        with self._inflight_lock:
            self._inflight += delta
            cur = self._inflight_by_method.get(method, 0) + delta
            self._inflight_by_method[method] = cur
            record_rpc_inflight(self._inflight)
            record_rpc_method_inflight(method, cur)

    def _admit(self, request, accepted_at: float | None = None):
        """Admission stage: cheap and non-blocking, so the async
        transport runs it ON THE EVENT LOOP before a request may take
        an executor slot.  Returns a finished error response for
        invalid/unknown/shed requests, or an _Admitted carrying the
        overload decision — which _execute() MUST release."""
        if not isinstance(request, dict) or "method" not in request:
            return _err(None, -32600, "invalid request")
        rid = request.get("id")
        method = request["method"]
        params = request.get("params") or []
        fn = self.methods.get(method)
        if fn is None:
            return _err(rid, -32601, f"method {method} not found")
        # admission control BEFORE any execution: a shed request is
        # answered with the typed busy error and never runs (and never
        # queues behind the executor), which is what keeps shed
        # responses cheap under sustained overload
        queue_age = None if accepted_at is None else \
            max(0.0, time.monotonic() - accepted_at)
        decision = self.overload.admit(method, queue_age)
        if not decision.admitted:
            return _err(rid, SERVER_BUSY_CODE, "server busy",
                        decision.error_data())
        return _Admitted(rid, method, params, fn, decision)

    def _execute(self, adm: _Admitted) -> dict:
        """Execution stage: the (possibly blocking) handler body.  The
        async transport runs it on the executor pool; direct handle()
        callers run it on their own thread."""
        rid, method = adm.rid, adm.method
        self._track_inflight(method, +1)
        t0 = time.perf_counter()
        # every request runs under a trace context, so nested spans
        # correlate and the slow-request log line carries the trace ID
        with trace_context(None) as trace_id:
            try:
                # chaos seat: a slow or crashing handler body
                inject("rpc.handle")
                result = adm.fn(*adm.params)
                return {"jsonrpc": "2.0", "id": rid, "result": result}
            except RpcError as ex:
                return _err(rid, ex.code, ex.message, ex.data)
            except TypeError as ex:
                return _err(rid, -32602, f"invalid params: {ex}")
            except Exception as ex:  # noqa: BLE001 — RPC boundary
                return _err(rid, -32603, f"internal error: {ex}")
            finally:
                self.overload.release(adm.decision)
                elapsed = time.perf_counter() - t0
                # known methods only, so label cardinality stays bounded;
                # the exemplar links the landing bucket to this request's
                # trace in the OpenMetrics exposition
                observe_rpc_request(method, elapsed, trace_id=trace_id)
                self._track_inflight(method, -1)
                if elapsed >= SLOW_REQUEST_SECONDS:
                    record_rpc_slow_request()
                    LOG.warning("slow rpc request method=%s "
                                "seconds=%.3f traceId=%s",
                                method, elapsed, trace_id)

    def handle(self, request: dict, accepted_at: float | None = None):
        """Synchronous admit+execute — the websocket dispatch path and
        direct callers (tests, tools) that bring their own thread."""
        adm = self._admit(request, accepted_at)
        if isinstance(adm, _Admitted):
            return self._execute(adm)
        return adm

    # -- async dispatch ------------------------------------------------
    def _get_executor(self) -> ThreadPoolExecutor:
        """Lazily build the bounded execution pool (shared with the
        websocket server's dispatch path)."""
        ex = self._executor
        if ex is None:
            with self._exec_lock:
                ex = self._executor
                if ex is None:
                    ex = ThreadPoolExecutor(
                        max_workers=self.executor_workers,
                        thread_name_prefix="rpc-exec")
                    record_rpc_executor_workers(self.executor_workers)
                    self._executor = ex
        return ex

    def _authorized(self, auth_header: str) -> bool:
        from .engine import jwt_verify

        token = auth_header.removeprefix("Bearer ").strip()
        return bool(token) and jwt_verify(self.jwt_secret, token)

    async def _respond(self, raw: bytes,
                       accepted_at: float | None) -> bytes:
        """One HTTP body → one response body (single or batch)."""
        try:
            try:
                req = json.loads(raw)
            except json.JSONDecodeError:
                resp = _err(None, -32700, "parse error")
            else:
                if isinstance(req, list):
                    resp = await self._handle_batch(req, accepted_at)
                else:
                    resp = await self._handle_async(req, accepted_at)
            data = json.dumps(resp).encode()
        except asyncio.CancelledError:
            raise
        except Exception as ex:  # noqa: BLE001 — transport boundary
            data = json.dumps(_err(None, -32603,
                                   f"internal error: {ex}")).encode()
        record_rpc_bytes(len(raw), len(data))
        return data

    async def _handle_async(self, request,
                            accepted_at: float | None = None):
        adm = self._admit(request, accepted_at)
        if not isinstance(adm, _Admitted):
            return adm
        loop = asyncio.get_running_loop()
        try:
            return await loop.run_in_executor(
                self._get_executor(), self._execute, adm)
        except RuntimeError:
            # executor already shutting down: _execute never ran, so
            # the admission slot is still held — release it here
            self.overload.release(adm.decision)
            return _err(adm.rid, -32603, "server shutting down")

    async def _handle_batch(self, reqs: list,
                            accepted_at: float | None = None):
        """JSON-RPC batch array: concurrent dispatch, in-order
        reassembly; malformed entries get per-entry errors, size
        violations a typed whole-batch error — never a closed
        connection."""
        n = len(reqs)
        if n == 0:
            return _err(None, -32600, "empty batch")
        if n > self.max_batch:
            return _err(None, -32600,
                        f"batch too large: {n} > {self.max_batch}")
        record_rpc_batch(n)
        return list(await asyncio.gather(
            *(self._handle_async(r, accepted_at) for r in reqs)))

    # ------------------------------------------------------------------
    def start(self):
        backlog = int(self.backlog) if self.backlog is not None \
            else DEFAULT_BACKLOG
        self._loop_thread = LoopThread(name="rpc-http-loop").start()
        self._aio_server = self._loop_thread.call(self._open(backlog))
        self.port = self._aio_server.sockets[0].getsockname()[1]
        self._httpd = _ListenerShim(self, backlog)
        record_rpc_backlog(backlog)
        return self

    async def _open(self, backlog: int):
        return await asyncio.start_server(
            self._serve, self.host, self.port, backlog=backlog,
            limit=_READER_LIMIT)

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter):
        conn = _HttpConn(self, reader, writer)
        self._conns.add(conn)
        record_rpc_accept()
        conn.reader_task = asyncio.current_task()
        conn.writer_task = asyncio.ensure_future(conn.write_loop())
        try:
            await conn.read_loop()
        except asyncio.CancelledError:
            pass  # draining: stop reading; the writer flushes + closes
        except Exception:  # noqa: BLE001 — one bad conn, not a crash
            LOG.debug("connection reader failed", exc_info=True)
        finally:
            conn.queue.put_nowait(None)

    async def _shutdown_async(self, drain: float | None):
        srv = self._aio_server
        if srv is not None:
            srv.close()
            await srv.wait_closed()
        conns = list(self._conns)
        for conn in conns:
            task = conn.reader_task
            if task is not None and not task.done():
                task.cancel()
        writers = [c.writer_task for c in conns
                   if c.writer_task is not None
                   and not c.writer_task.done()]
        if writers:
            # graceful drain: cancelled readers enqueue the sentinel,
            # so each writer exits once in-flight responses are flushed
            _, stuck = await asyncio.wait(
                writers, timeout=drain if drain is not None else 0.25)
            for task in stuck:
                task.cancel()
        for conn in conns:
            conn.abort()

    def stop(self, drain: float | None = None):
        """Stop accepting, drain in-flight requests for up to `drain`
        seconds (the shutdown manager passes its remaining budget),
        then close every connection, the executor pool and the loop."""
        lt = self._loop_thread
        if lt is not None:
            self._loop_thread = None
            try:
                lt.call(self._shutdown_async(drain),
                        timeout=(drain or 0.0) + 5.0)
            except Exception:  # noqa: BLE001 — hard-stop below reclaims
                pass
            lt.stop()
            self._aio_server = None
        ex = self._executor
        if ex is not None:
            self._executor = None
            ex.shutdown(wait=True)


def _peer_count(node) -> int:
    p2p = getattr(node, "p2p_server", None)
    return len(p2p.peers) if p2p else 0


def _sha3(data) -> str:
    from ..crypto.keccak import keccak256

    if not isinstance(data, str):
        raise RpcError(-32602, "web3_sha3 expects a hex string")
    try:
        raw = bytes.fromhex(data.removeprefix("0x"))
    except ValueError as e:
        raise RpcError(-32602, f"invalid hex data: {e}")
    return "0x" + keccak256(raw).hex()


def _err(rid, code, message, data=None):
    error = {"code": code, "message": message}
    if data is not None:
        error["data"] = data
    return {"jsonrpc": "2.0", "id": rid, "error": error}


def _get_nonce_fn(node):
    head = node.store.get_canonical_block(node.store.latest_number())

    def get_nonce(sender: bytes) -> int:
        acct = node.store.account_state(head.header.state_root, sender)
        return acct.nonce if acct else 0

    return get_nonce


def _txpool_content(node):
    from .serializers import tx_to_json

    pending, queued = node.mempool.split(_get_nonce_fn(node))

    def fmt(part):
        return {
            "0x" + sender.hex(): {
                str(nonce): tx_to_json(tx) for nonce, tx in queue.items()
            } for sender, queue in part.items()
        }

    return {"pending": fmt(pending), "queued": fmt(queued)}


def _txpool_status(node):
    counts = node.mempool.status(_get_nonce_fn(node))
    return {"pending": hex(counts["pending"]),
            "queued": hex(counts["queued"])}


def _admin_node_info(node):
    """admin_nodeInfo (reference: admin namespace, rpc.rs)."""
    p2p = getattr(node, "p2p_server", None)
    genesis = node.store.meta.get("genesis")
    info = {
        "name": f"{CLIENT_NAME}/{CLIENT_VERSION}",
        "protocols": {
            "eth": {
                "network": node.config.chain_id,
                "genesis": "0x" + genesis.hex() if genesis else None,
            },
        },
    }
    if p2p is not None:
        info["enode"] = (f"enode://{p2p.pub.hex()}"
                         f"@{p2p.host}:{p2p.port}")
        info["listenAddr"] = f"{p2p.host}:{p2p.port}"
        info["id"] = p2p.pub.hex()
    return info


def _admin_peers(node):
    p2p = getattr(node, "p2p_server", None)
    if p2p is None:
        return []
    out = []
    for peer in list(p2p.peers):
        try:
            host, port = peer.sock.getpeername()[:2]
        except OSError:
            host, port = "", 0
        entry = {
            "id": bytes(peer.remote_pub).hex(),
            "network": {"remoteAddress": f"{host}:{port}"},
            "score": getattr(peer, "score", 0),
        }
        status = peer.remote_status
        if status is not None:
            entry["protocols"] = {"eth": {
                "version": status.version,
                "head": "0x" + status.head_hash.hex(),
            }}
        out.append(entry)
    return out


def _produce(node):
    block = node.produce_block()
    return "0x" + block.hash.hex()


def _rollup(node):
    seq = getattr(node, "sequencer", None)
    if seq is None:
        raise RpcError(-32000, "node is not running an L2 sequencer")
    return seq


def _batch_json(batch, rollup):
    from .serializers import hb, hx

    with rollup.lock:  # a half-applied set_committed must not leak out
        return {
            "number": hx(batch.number),
            "firstBlock": hx(batch.first_block),
            "lastBlock": hx(batch.last_block),
            "stateRoot": hb(batch.state_root),
            "commitment": hb(batch.commitment),
            "committed": batch.committed,
            "verified": batch.verified,
        }


def _latest_batch(node):
    seq = _rollup(node)
    n = seq.rollup.latest_batch_number()
    batch = seq.rollup.get_batch(n)
    return _batch_json(batch, seq.rollup) if batch else None


def _get_batch(node, n):
    from .serializers import parse_quantity

    seq = _rollup(node)
    batch = seq.rollup.get_batch(parse_quantity(n))
    return _batch_json(batch, seq.rollup) if batch else None


def _find_batch_for_block(seq, block_number):
    with seq.rollup.lock:
        for n in sorted(seq.rollup.batches):
            b = seq.rollup.batches[n]
            if b.first_block <= block_number <= b.last_block:
                return b
    return None


def _batch_by_block(node, n):
    """ethrex_batchNumberByBlock: which batch carries an L2 block."""
    from .serializers import hx, parse_quantity

    seq = _rollup(node)
    batch = _find_batch_for_block(seq, parse_quantity(n))
    return hx(batch.number) if batch else None


def _l1_message_proof(node, tx_hash_hex):
    """ethrex_getL1MessageProof: the withdrawal claim data for a tx —
    its batch, message index, leaf hash and Merkle path against the
    batch's message root (reference:
    crates/l2/networking/rpc/l2/messages.rs GetL1MessageProof)."""
    from ..l2.messages import collect_messages, message_proof, message_root
    from .serializers import hb, hx, parse_bytes

    seq = _rollup(node)
    tx_hash = parse_bytes(tx_hash_hex)
    # canonical-verified: an orphaned inclusion has no message proof
    loc = node.store.canonical_tx_location(tx_hash)
    if loc is None:
        return None
    header = node.store.get_header(loc[0])
    if header is None:
        return None
    block_number = header.number
    batch = _find_batch_for_block(seq, block_number)
    if batch is None:
        return None
    blocks = [node.store.get_canonical_block(n)
              for n in range(batch.first_block, batch.last_block + 1)]
    if any(b is None for b in blocks):
        return None
    receipts = [node.store.get_receipts(b.hash) for b in blocks]
    if any(r is None for r in receipts):
        # a message set built without the success filter would diverge
        # from the committed root and serve an unclaimable proof
        raise RpcError(-32000, "missing receipts for a batched block")
    messages = collect_messages(blocks, receipts)
    for idx, msg in enumerate(messages):
        if msg.tx_hash == tx_hash:
            return {
                "batchNumber": hx(batch.number),
                "messageId": hx(idx),
                "messageHash": hb(msg.leaf()),
                "merkleProof": [hb(p)
                                for p in message_proof(messages, idx)],
                "messageRoot": hb(message_root(messages)),
                "verified": batch.verified,
            }
    return None


def _require_admin(server):
    """Admin control methods live behind an explicit opt-in: the public
    unauthenticated RPC must not let any client halt batch commitment
    (the reference keeps these on a dedicated admin listener,
    admin_server.rs; here `RpcServer(admin=True)` / --l2.admin)."""
    if not getattr(server, "admin_enabled", False):
        raise RpcError(-32601, "admin methods are disabled "
                               "(start with admin enabled)")


def _admin_committer(server, node, start: bool, delay=0):
    """ethrex_adminStart/StopCommitter: pause/resume the L1 committer
    actor, optionally delayed (reference: admin_server.rs
    /committer/start/{delay} and /committer/stop)."""
    from .serializers import parse_quantity

    _require_admin(server)
    seq = _rollup(node)
    name = "commit_next_batch"
    if start:
        seq.resume_actor(name, float(parse_quantity(delay)
                                     if isinstance(delay, str) else delay))
    else:
        seq.pause_actor(name)
    return {"committer": "running" if start else "paused"}


def _admin_stop_at(server, node, n):
    """ethrex_adminSetStopAtBatch: the committer stops producing batch
    checkpoints past this number; null clears the cap
    (admin_server.rs set_sequencer_stop_at)."""
    from .serializers import hx, parse_quantity

    _require_admin(server)
    seq = _rollup(node)
    seq.stop_at_batch = None if n is None else parse_quantity(n)
    return {"stopAtBatch": None if seq.stop_at_batch is None
            else hx(seq.stop_at_batch)}


def _trace_limit(limit) -> int:
    """ethrex_trace_* limit param: JSON int or 0x-quantity, default 20."""
    if limit is None:
        return 20
    if isinstance(limit, str):
        from .serializers import parse_quantity

        return parse_quantity(limit)
    return int(limit)


def _resolve_trace(tid):
    """Trace dict for an explicit ID, or the slowest buffered trace when
    the caller passed none.  None means the ring has nothing to offer —
    pre-tracing / L1-only / idle nodes — and the trace analysis RPCs
    degrade to a found=False stub rather than an error."""
    if tid is None:
        slow = TRACER.slowest(1)
        if not slow:
            return None
        return {"traceId": slow[0]["traceId"], "spans": slow[0]["spans"]}
    if not isinstance(tid, str):
        return None
    return TRACER.get_trace(tid)


def _trace_critical_path(tid=None):
    """ethrex_trace_criticalPath: blocking chain + per-component wall
    attribution of one merged trace (default: the slowest buffered one).
    See docs/OBSERVABILITY.md "Distributed tracing"."""
    from ..utils.tracing import critical_path

    trace = _resolve_trace(tid)
    if trace is None:
        return {"found": False, "traceId": tid, "components": {},
                "chain": []}
    out = {"found": True}
    out.update(critical_path(trace))
    return out


def _trace_export(tid=None):
    """ethrex_trace_export: one merged trace as Chrome trace-event JSON,
    loadable directly in Perfetto / chrome://tracing."""
    from ..utils.tracing import to_trace_events

    trace = _resolve_trace(tid)
    if trace is None:
        return {"found": False, "traceId": tid, "traceEvents": []}
    out = {"found": True}
    out.update(to_trace_events(trace))
    return out


def _alerts(node):
    """ethrex_alerts: alert-engine state, degrading to a disabled stub
    on nodes that never attached an engine (L1-only / older nodes)."""
    eng = getattr(node, "alerts", None)
    if eng is None:
        return {"enabled": False, "rules": [], "active": [], "recent": []}
    out = {"enabled": True}
    out.update(eng.to_json())
    return out


def _perf(node):
    """ethrex_perf: stage-attribution tree + roofline report + live
    throughput gauges.  The profiler and roofline registries are
    process-global, so this answers on every node flavor; sections that
    fail (or never populated — e.g. roofline on an L1-only node that
    never compiled a prover kernel) degrade to stubs, not errors."""
    out = {"enabled": True}
    try:
        from ..perf import profiler
        out["profiler"] = profiler.PROFILER.tree()
    except Exception as exc:  # noqa: BLE001 — telemetry endpoint
        out["profiler"] = {"error": f"{type(exc).__name__}: {exc}"}
    try:
        from ..perf import roofline
        out["roofline"] = roofline.ROOFLINE.report()
    except Exception as exc:  # noqa: BLE001 — telemetry endpoint
        out["roofline"] = {"error": f"{type(exc).__name__}: {exc}"}
    try:
        from ..utils.metrics import METRICS
        with METRICS.lock:
            gauges = dict(METRICS.gauges)
        out["throughput"] = {
            name: gauges.get(name)
            for name in ("l1_import_mgas_per_sec",
                         "prover_trace_cells_per_sec",
                         "proofs_per_hour")
        }
        out["mesh"] = {
            "devices": gauges.get("prover_mesh_devices"),
            "vmCircuitsParallel":
                gauges.get("prover_vm_circuits_parallel"),
        }
    except Exception as exc:  # noqa: BLE001 — telemetry endpoint
        out["throughput"] = {"error": f"{type(exc).__name__}: {exc}"}
    try:
        from ..utils import exec_cache
        out["executableCache"] = exec_cache.runtime_stats()
    except Exception as exc:  # noqa: BLE001 — telemetry endpoint
        out["executableCache"] = {
            "error": f"{type(exc).__name__}: {exc}"}
    # scaling-autopsy sections (PR 18): HLO collective accounting and
    # device-occupancy timelines.  Both registries answer an empty
    # stub on L1-only / pre-autopsy nodes — the monitor renders, never
    # KeyErrors (regression-tested in tests/test_scaling_autopsy.py).
    try:
        from ..perf import hlo_introspect
        out["collectives"] = hlo_introspect.REGISTRY.report()
    except Exception as exc:  # noqa: BLE001 — telemetry endpoint
        out["collectives"] = {"error": f"{type(exc).__name__}: {exc}"}
    try:
        from ..perf import occupancy
        out["occupancy"] = occupancy.REGISTRY.report()
    except Exception as exc:  # noqa: BLE001 — telemetry endpoint
        out["occupancy"] = {"error": f"{type(exc).__name__}: {exc}"}
    return out


def _chain_path(node):
    """ethrex_chainPath: the chain-path X-ray — per-stage queue stats
    (depth, arrival/service rates, utilization, Little's-law check),
    sampled per-tx lifecycle records and the bottleneck explainer
    (docs/OBSERVABILITY.md "Chain-path telemetry").  The instrument is
    process-global; on an L1-only node that never produces blocks it
    answers an idle stub (zero queues, bottleneck null), never an
    error."""
    try:
        from ..perf.chain_path import CHAIN_PATH

        return CHAIN_PATH.to_json()
    except Exception as exc:  # noqa: BLE001 — telemetry endpoint
        return {"enabled": False,
                "error": f"{type(exc).__name__}: {exc}"}


def _debug_snapshot(node):
    """ethrex_debug_snapshot: return a flight-recorder bundle, and
    persist it when --debug-snapshot-dir configured a destination."""
    from ..utils import snapshot

    bundle = snapshot.collect(node, reason="rpc")
    path = snapshot.write(node, reason="rpc", bundle=bundle)
    if path is not None:
        bundle["path"] = path
    return bundle


def _rpc_traffic_json() -> dict:
    """Request-lifecycle counters/gauges for ethrex_health: connection
    churn, in-flight work, byte totals and the configured backlog —
    read straight from the global registry."""
    with METRICS.lock:
        c = dict(METRICS.counters)
        g = dict(METRICS.gauges)
    return {
        "accepted": int(c.get("rpc_connections_accepted_total", 0)),
        "resets": int(c.get("rpc_connections_reset_total", 0)),
        "eof": int(c.get("rpc_connections_eof_total", 0)),
        "inflight": int(g.get("rpc_inflight_requests", 0)),
        "listenBacklog": g.get("rpc_listen_backlog"),
        "requestBytes": int(c.get("rpc_request_bytes_total", 0)),
        "responseBytes": int(c.get("rpc_response_bytes_total", 0)),
        "slowRequests": int(c.get("rpc_slow_requests_total", 0)),
        "shed": int(c.get("rpc_requests_shed_total", 0)),
        "shedLevel": int(g.get("rpc_shed_level", 0)),
        "wsConnections": int(g.get("ws_connections", 0)),
        "wsNotifications": int(c.get("ws_notifications_total", 0)),
        "wsSendFailures": int(c.get("ws_send_failures_total", 0)),
        "wsNotificationsDropped":
            int(c.get("ws_notifications_dropped_total", 0)),
        "wsSlowConsumerDisconnects":
            int(c.get("ws_slow_consumer_disconnects_total", 0)),
    }


def _p2p_json(node) -> dict:
    """P2P request-resilience and snap-sync counters for ethrex_health:
    timeout/retry/ban totals plus the snap phase machine — read straight
    from the global registry (docs/P2P_RESILIENCE.md)."""
    with METRICS.lock:
        c = dict(METRICS.counters)
        g = dict(METRICS.gauges)
    out = {
        "peers": _peer_count(node),
        "requestTimeouts": int(c.get("p2p_request_timeouts_total", 0)),
        "requestRetries": int(c.get("p2p_request_retries_total", 0)),
        "peerBans": int(c.get("p2p_peer_bans_total", 0)),
        "broadcastFailures":
            int(c.get("p2p_broadcast_failures_total", 0)),
        "snap": {
            "phase": int(g.get("snap_sync_phase", 0)),
            "rangesSynced": int(c.get("snap_ranges_synced_total", 0)),
            "paused": bool(g.get("snap_sync_paused", 0)),
            "partitionPauses":
                int(c.get("snap_partition_pauses_total", 0)),
            "progressResets":
                int(c.get("snap_progress_resets_total", 0)),
        },
    }
    p2p = getattr(node, "p2p_server", None)
    bans = getattr(p2p, "bans", None)
    if bans is not None:
        out["activeBans"] = len(bans.active())
    return out


def _mempool_util(node) -> float | None:
    """Mempool fill fraction for the overload controller's shed-level
    feedback; None (never sheds) when the node has no mempool."""
    mempool = getattr(node, "mempool", None)
    return mempool.utilization() if mempool is not None else None


def _health(node):
    out = {
        "head": node.store.latest_number(),
        "mempool": len(node.mempool),
        "mempoolFlow": node.mempool.stats_json(),
        "rpc": _rpc_traffic_json(),
        "peers": _peer_count(node),
        "p2p": _p2p_json(node),
        "tracing": {"bufferedTraces": len(TRACER),
                    "droppedTraces": TRACER.dropped,
                    # span-shipping ingestion health: remote spans merged
                    # into (or dropped by) the ring
                    "spansIngested": TRACER.ingested,
                    "spanIngestDropped": TRACER.ingest_dropped},
    }
    reorg_handler = getattr(node, "reorg_handler", None)
    if reorg_handler is not None:
        # reorg posture (docs/CHAIN_RESILIENCE.md): totals, depths, the
        # mempool re-injection/eviction ledger, and whether a pending
        # reorg journal awaits replay (should only be true mid-crash)
        out["chain"] = reorg_handler.stats_json()
    overload = getattr(node, "rpc_overload", None)
    if overload is not None:
        out["rpc"]["overload"] = overload.to_json()
    alerts = getattr(node, "alerts", None)
    if alerts is not None:
        active = alerts.active()
        out["alerts"] = {
            "firing": len(active),
            "page": sum(1 for a in active if a["severity"] == "page"),
            "warn": sum(1 for a in active if a["severity"] == "warn"),
            "active": [a["name"] for a in active],
            "transitions": alerts.transitions_total,
        }
    telemetry = getattr(node, "telemetry", None)
    if telemetry is not None:
        out["telemetry"] = {"samples": len(telemetry.samples),
                            "samplerRunning": telemetry.running(),
                            "samplerErrors": telemetry.sampler_errors}
    sd = getattr(node, "shutdown", None)
    if sd is not None:
        out["shutdown"] = {"phase": sd.phase,
                           "durationSeconds": sd.duration}
    try:
        from ..perf import profiler, roofline

        rep = roofline.ROOFLINE.report()
        tree = profiler.PROFILER.tree()
        kernels = rep.get("kernels") or []
        utils = [k["utilizationVsPeak"] for k in kernels
                 if k.get("utilizationVsPeak") is not None]
        from ..crypto import native_secp256k1

        out["perf"] = {
            "componentsProfiled": sorted(tree.get("components", {})),
            "kernelsProfiled": len(kernels),
            "maxUtilizationVsPeak": max(utils) if utils else None,
            # which sender-recovery engine is live: the native C engine
            # or the pure-Python fallback (docs/PERFORMANCE.md)
            "nativeSecp256k1": native_secp256k1.available(),
        }
        from ..utils import exec_cache

        cache = exec_cache.runtime_stats()
        # cold-start posture: are AOT kernels hydrating from disk or
        # being recompiled? (docs/PERFORMANCE.md "Cold start")
        out["perf"]["executableCache"] = {
            k: cache.get(k)
            for k in ("hits", "misses", "errors", "entries", "enabled")}
        # scaling-autopsy posture (PR 18): kernel rows with collective
        # accounting and the last prove's device occupancy — None/0 on
        # L1-only nodes, never an error
        from ..perf import hlo_introspect, occupancy

        coll = hlo_introspect.REGISTRY.report().get("kernels") or []
        occ = occupancy.REGISTRY.report()
        last = occ.get("lastProve") or {}
        out["perf"]["kernelsIntrospected"] = len(coll)
        out["perf"]["collectiveOpsTotal"] = sum(
            k.get("collectiveOps") or 0 for k in coll)
        out["perf"]["deviceOccupancy"] = last.get("occupancy")
    except Exception:  # noqa: BLE001 — health must answer regardless
        pass
    try:
        # chain-path posture (docs/OBSERVABILITY.md "Chain-path
        # telemetry"): stage depths/utilizations, live inclusion tps and
        # the named bottleneck.  L1-only nodes (no producer) answer the
        # idle stub — bottleneck null, zero queues — never an error.
        from ..perf.chain_path import CHAIN_PATH

        out["chainPath"] = CHAIN_PATH.health_json()
    except Exception:  # noqa: BLE001 — health must answer regardless
        pass
    seq = getattr(node, "sequencer", None)
    if seq is not None:
        from ..storage.persistent import storage_stats
        from ..utils import shutdown as _shutdown

        stats = storage_stats()
        out["l2"] = {
            "latestBatch": seq.rollup.latest_batch_number(),
            "lastBatchedBlock": seq.last_batched_block,
            "pendingPrivileged": len(seq.pending_privileged),
            "actors": {name: st.to_json()
                       for name, st in seq.health.items()},
            # admin state: a deliberately paused actor must be
            # distinguishable from a stuck one (review finding)
            "paused": sorted(seq.paused),
            "resumeAt": dict(seq._resume_at),
            "stopAtBatch": seq.stop_at_batch,
            "fatal": list(seq.fatal) if seq.fatal else None,
            # prover pipeline resilience: lease/reassignment counters and
            # the poison-batch quarantine (docs/PROVER_RESILIENCE.md);
            # the fleet scheduler state rides inside under "scheduler"
            "prover": seq.coordinator.stats_json(),
            # per-batch lifecycle timeline: critical-path summaries of
            # recently settled batches' merged traces
            # (docs/OBSERVABILITY.md "Distributed tracing")
            "lifecycle": seq.coordinator.lifecycles_json(),
            # recursive aggregation pipeline state (docs/AGGREGATION.md)
            "aggregation": {
                "enabled": seq.cfg.aggregation_enabled,
                **seq.aggregator.stats_json(),
            },
            # L1 settlement resilience: reorg/recommit/adoption counters
            # and the recommit backlog (docs/L1_SETTLEMENT_RESILIENCE.md)
            "l1": {
                "reorgs": seq.reorgs_total,
                "recommitted": seq.recommits_total,
                "adoptedCommits": seq.commits_adopted_total,
                "rebuiltBatches": seq.rebuilt_batches_total,
                "recommitQueue": sorted(seq._recommit_queue),
                "confirmationDepth": seq.cfg.l1_confirmation_depth,
            },
            # storage resilience: corruption/rebuild/journal counters and
            # the last drain duration (docs/STORAGE_RESILIENCE.md)
            "store": {
                "corruptRecords": stats["corrupt_records"],
                "rebuiltRecords": stats["rebuilt_records"],
                "journalReplays": stats["journal_replays"],
                "journalDiscards": stats["journal_discards"],
                "lastShutdownSeconds": _shutdown.LAST_DURATION,
            },
        }
        # HA leader election state (docs/SEQUENCER_HA.md): role, epoch,
        # transition/fence counters and the last promotion's downtime
        leadership = getattr(seq, "leadership", None)
        if leadership is not None:
            out["l2"]["leadership"] = leadership.status()
    return out


def _ready(node):
    """ethrex_ready: readiness (can THIS node serve as sequencer right
    now?) as opposed to ethrex_health's liveness.  A hot standby is
    perfectly healthy yet NOT ready — load balancers and failover drills
    key off this method (docs/SEQUENCER_HA.md)."""
    seq = getattr(node, "sequencer", None)
    if seq is None:
        # an L1-only node is "ready" in the serving sense as soon as it
        # answers RPC at all; there is no sequencer role to gate on
        return {"ready": True, "role": None, "ha": False}
    return seq.ready_json()
