"""JSON-RPC HTTP server + method routing (parity target: the reference's
crates/networking/rpc/rpc.rs start_api; threaded stdlib HTTP server is the
round-1 transport, the C++ server replaces it behind the same handlers)."""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils.faults import inject
from ..utils.metrics import (METRICS, observe_rpc_queue_wait,
                             observe_rpc_request, record_rpc_accept,
                             record_rpc_backlog, record_rpc_bytes,
                             record_rpc_eof, record_rpc_inflight,
                             record_rpc_method_inflight, record_rpc_reset,
                             record_rpc_slow_request)
from ..utils.overload import SERVER_BUSY_CODE, OverloadController
from ..utils.tracing import TRACER, trace_context

from .eth import (CLIENT_NAME, CLIENT_VERSION, EthApi,
                  RpcError)  # noqa: F401 (RpcError used below)

LOG = logging.getLogger("ethrex.rpc")

# Requests slower than this emit one structured log line (with the trace
# ID) and bump rpc_slow_requests_total.  Env override so operators can
# tighten it without a restart script change.
SLOW_REQUEST_SECONDS = float(os.environ.get("ETHREX_RPC_SLOW_SECONDS",
                                            "1.0"))
DEFAULT_BACKLOG = 128

# per-handler-thread accept-wait handoff: finish_request stamps the
# accept->handler wait here; the FIRST request on the connection
# consumes it (keep-alive connections serve many requests per handler
# thread — later requests never sat in the accept queue, so charging
# them the connection's accept wait would shed healthy persistent
# clients)
_TLS = threading.local()


class _Httpd(ThreadingHTTPServer):
    # The socketserver default backlog of 5 lets the kernel RST
    # connections when a burst of clients connects faster than the
    # accept loop drains (the reset shows up client-side as
    # ConnectionResetError 104, not a clean HTTP error).  Configurable
    # via --rpc-backlog / ETHREX_RPC_BACKLOG; saturation shows up in
    # rpc_connections_reset_total instead of silent kernel RSTs.
    request_queue_size = DEFAULT_BACKLOG

    def __init__(self, addr, handler, backlog: int | None = None):
        if backlog is not None:
            # instance attribute shadows the class default; read by
            # server_activate() -> socket.listen()
            self.request_queue_size = int(backlog)
        # accept timestamps keyed by connection object id: stamped on
        # the accept-loop thread (process_request), consumed on the
        # handler thread (finish_request) — the queue-wait measurement
        self._accepted_at: dict[int, float] = {}
        super().__init__(addr, handler)

    def process_request(self, request, client_address):
        self._accepted_at[id(request)] = time.monotonic()
        record_rpc_accept()
        super().process_request(request, client_address)

    def finish_request(self, request, client_address):
        t0 = self._accepted_at.pop(id(request), None)
        if t0 is not None:
            wait = time.monotonic() - t0
            observe_rpc_queue_wait(wait)
            _TLS.accept_wait = wait
        super().finish_request(request, client_address)


class RpcServer:
    def __init__(self, node, host: str = "127.0.0.1", port: int = 8545,
                 jwt_secret: bytes | None = None, engine: bool = False,
                 admin: bool = False, backlog: int | None = None,
                 overload: OverloadController | None = None):
        self.node = node
        self.eth = EthApi(node)
        self.host = host
        self.port = port
        self.jwt_secret = jwt_secret
        self.admin_enabled = admin
        self.backlog = backlog
        # admission control (docs/OVERLOAD.md): mempool utilization
        # feeds the shed ladder so tx submission sheds before the pool
        # starts thrashing its eviction queues
        self.overload = overload if overload is not None else \
            OverloadController(mempool_probe=lambda: _mempool_util(node))
        # expose the controller for health/snapshot surfaces that only
        # hold the node (last-attached server wins, single-node truth)
        node.rpc_overload = self.overload
        self._httpd: ThreadingHTTPServer | None = None
        self._inflight_lock = threading.Lock()
        self._inflight = 0
        self._inflight_by_method: dict[str, int] = {}
        self.methods = self._build_methods()
        if engine:
            from .engine import EngineApi

            api = EngineApi(node)
            self.engine_api = api
            self.methods.update({
                "engine_exchangeCapabilities": api.exchange_capabilities,
                "engine_newPayloadV1": api.new_payload_v1,
                "engine_newPayloadV2": api.new_payload_v2,
                "engine_newPayloadV3": api.new_payload_v3,
                "engine_newPayloadV4": api.new_payload_v4,
                "engine_forkchoiceUpdatedV1": api.forkchoice_updated_v1,
                "engine_forkchoiceUpdatedV2": api.forkchoice_updated_v2,
                "engine_forkchoiceUpdatedV3": api.forkchoice_updated_v3,
                "engine_getPayloadV1": api.get_payload_v1,
                "engine_getPayloadV2": api.get_payload_v2,
                "engine_getPayloadV3": api.get_payload_v3,
                "engine_getPayloadV4": api.get_payload_v4,
                "engine_getPayloadBodiesByHashV1":
                    api.get_payload_bodies_by_hash_v1,
                "engine_getPayloadBodiesByRangeV1":
                    api.get_payload_bodies_by_range_v1,
                "engine_getClientVersionV1": api.get_client_version_v1,
            })

    def _build_methods(self):
        e = self.eth
        node = self.node
        return {
            "eth_chainId": lambda: e.chain_id(),
            "eth_blockNumber": lambda: e.block_number(),
            "eth_getBalance": e.get_balance,
            "eth_getTransactionCount": e.get_transaction_count,
            "eth_getCode": e.get_code,
            "eth_getStorageAt": e.get_storage_at,
            "eth_gasPrice": lambda: e.gas_price(),
            "eth_maxPriorityFeePerGas": lambda: e.max_priority_fee_per_gas(),
            "eth_syncing": lambda: e.syncing(),
            "eth_getBlockByNumber": e.get_block_by_number,
            "eth_getBlockByHash": e.get_block_by_hash,
            "eth_getTransactionByHash": e.get_transaction_by_hash,
            "eth_getTransactionReceipt": e.get_transaction_receipt,
            "eth_getBlockReceipts": e.get_block_receipts,
            "eth_getLogs": e.get_logs,
            "eth_newFilter": e.new_filter,
            "eth_newBlockFilter": lambda: e.new_block_filter(),
            "eth_newPendingTransactionFilter":
                lambda: e.new_pending_transaction_filter(),
            "eth_getFilterChanges": e.get_filter_changes,
            "eth_getFilterLogs": e.get_filter_logs,
            "eth_uninstallFilter": e.uninstall_filter,
            "eth_call": e.call,
            "eth_estimateGas": e.estimate_gas,
            "eth_sendRawTransaction": e.send_raw_transaction,
            "eth_feeHistory": e.fee_history,
            "eth_getProof": e.get_proof,
            "debug_executionWitness": e.debug_execution_witness,
            "debug_traceTransaction": e.debug_trace_transaction,
            "net_version": lambda: str(node.config.chain_id),
            "net_listening": lambda: True,
            "net_peerCount": lambda: hex(_peer_count(node)),
            "web3_clientVersion":
                lambda: f"{CLIENT_NAME}/{CLIENT_VERSION}",
            "web3_sha3": _sha3,
            "eth_blobBaseFee": lambda: e.blob_base_fee(),
            "eth_getBlockTransactionCountByNumber": e.block_tx_count,
            "eth_getBlockTransactionCountByHash":
                e.block_tx_count_by_hash,
            "eth_getTransactionByBlockNumberAndIndex":
                e.tx_by_block_and_index,
            "txpool_content": lambda: _txpool_content(node),
            "txpool_status": lambda: _txpool_status(node),
            "admin_nodeInfo": lambda: _admin_node_info(node),
            "admin_peers": lambda: _admin_peers(node),
            # post-merge constants / wallet compatibility
            "eth_accounts": lambda: [],
            "eth_mining": lambda: False,
            "eth_hashrate": lambda: "0x0",
            # uncles are always empty post-merge, but unknown blocks
            # must still answer null (matching block_tx_count's convention)
            "eth_getUncleCountByBlockHash":
                lambda h: None if e.block_tx_count_by_hash(h) is None
                else "0x0",
            "eth_getUncleCountByBlockNumber":
                lambda n: None if e.block_tx_count(n) is None else "0x0",
            "eth_getUncleByBlockHashAndIndex": lambda h, i: None,
            "eth_getUncleByBlockNumberAndIndex": lambda n, i: None,
            "ethrex_produceBlock": lambda: _produce(node),
            # L2 namespace (reference: crates/l2/networking/rpc)
            "ethrex_latestBatch": lambda: _latest_batch(node),
            "ethrex_getBatchByNumber": lambda n: _get_batch(node, n),
            "ethrex_health": lambda: _health(node),
            "ethrex_getL1MessageProof":
                lambda h: _l1_message_proof(node, h),
            "ethrex_batchNumberByBlock":
                lambda n: _batch_by_block(node, n),
            "ethrex_adminStopCommitter":
                lambda: _admin_committer(self, node, False),
            "ethrex_adminStartCommitter":
                lambda *a: _admin_committer(self, node, True,
                                            *(a[:1] or (0,))),
            "ethrex_adminSetStopAtBatch":
                lambda n=None: _admin_stop_at(self, node, n),
            # tracing namespace: serve the in-process trace ring buffer
            "ethrex_trace_recentTraces":
                lambda limit=None: TRACER.recent(_trace_limit(limit)),
            "ethrex_trace_slowest":
                lambda limit=None: TRACER.slowest(_trace_limit(limit)),
            # SLO/alert engine + flight recorder (docs/OBSERVABILITY.md)
            "ethrex_alerts": lambda: _alerts(node),
            "ethrex_debug_snapshot": lambda: _debug_snapshot(node),
            # continuous profiler + roofline (docs/PERFORMANCE.md)
            "ethrex_perf": lambda: _perf(node),
        }

    def _track_inflight(self, method: str, delta: int):
        with self._inflight_lock:
            self._inflight += delta
            cur = self._inflight_by_method.get(method, 0) + delta
            self._inflight_by_method[method] = cur
            record_rpc_inflight(self._inflight)
            record_rpc_method_inflight(method, cur)

    def handle(self, request: dict, accepted_at: float | None = None):
        if "method" not in request:
            return _err(None, -32600, "invalid request")
        rid = request.get("id")
        method = request["method"]
        params = request.get("params") or []
        fn = self.methods.get(method)
        if fn is None:
            return _err(rid, -32601, f"method {method} not found")
        # admission control BEFORE any execution: a shed request is
        # answered with the typed busy error and never runs, which is
        # what keeps shed responses cheap under sustained overload
        queue_age = None if accepted_at is None else \
            max(0.0, time.monotonic() - accepted_at)
        decision = self.overload.admit(method, queue_age)
        if not decision.admitted:
            return _err(rid, SERVER_BUSY_CODE, "server busy",
                        decision.error_data())
        self._track_inflight(method, +1)
        t0 = time.perf_counter()
        # every request runs under a trace context, so nested spans
        # correlate and the slow-request log line carries the trace ID
        with trace_context(None) as trace_id:
            try:
                # chaos seat: a slow or crashing handler body
                inject("rpc.handle")
                result = fn(*params)
                return {"jsonrpc": "2.0", "id": rid, "result": result}
            except RpcError as ex:
                return _err(rid, ex.code, ex.message, ex.data)
            except TypeError as ex:
                return _err(rid, -32602, f"invalid params: {ex}")
            except Exception as ex:  # noqa: BLE001 — RPC boundary
                return _err(rid, -32603, f"internal error: {ex}")
            finally:
                self.overload.release(decision)
                elapsed = time.perf_counter() - t0
                # known methods only, so label cardinality stays bounded
                observe_rpc_request(method, elapsed)
                self._track_inflight(method, -1)
                if elapsed >= SLOW_REQUEST_SECONDS:
                    record_rpc_slow_request()
                    LOG.warning("slow rpc request method=%s "
                                "seconds=%.3f traceId=%s",
                                method, elapsed, trace_id)

    # ------------------------------------------------------------------
    def start(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                try:
                    self._do_post()
                except (ConnectionResetError, BrokenPipeError):
                    # the client hung up mid-request/mid-response — the
                    # backlog-pressure signal, never a server traceback
                    record_rpc_reset()
                    self.close_connection = True

            def _do_post(self):
                if server.jwt_secret is not None:
                    from .engine import jwt_verify

                    auth = self.headers.get("Authorization", "")
                    token = auth.removeprefix("Bearer ").strip()
                    if not token or not jwt_verify(server.jwt_secret, token):
                        self.send_response(401)
                        self.end_headers()
                        self.wfile.write(b"unauthorized")
                        return
                # queue-age accounting: the first request on this
                # connection carries the accept->handler wait stamped
                # by finish_request; follow-ups on the same keep-alive
                # connection never queued, so their age starts here
                wait = getattr(_TLS, "accept_wait", None)
                if wait is not None:
                    _TLS.accept_wait = None
                    server.overload.note_queue_wait(wait)
                accepted_at = time.monotonic() - (wait or 0.0)
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                if len(body) < length:
                    # peer closed before the full body arrived
                    record_rpc_eof()
                    self.close_connection = True
                    return
                try:
                    req = json.loads(body)
                except json.JSONDecodeError:
                    resp = _err(None, -32700, "parse error")
                else:
                    if isinstance(req, list):
                        resp = [server.handle(r, accepted_at=accepted_at)
                                for r in req]
                    else:
                        resp = server.handle(req,
                                             accepted_at=accepted_at)
                data = json.dumps(resp).encode()
                record_rpc_bytes(len(body), len(data))
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *args):
                pass

        self._httpd = _Httpd((self.host, self.port), Handler,
                             backlog=self.backlog)
        self.port = self._httpd.server_address[1]
        record_rpc_backlog(self._httpd.request_queue_size)
        thread = threading.Thread(target=self._httpd.serve_forever,
                                  daemon=True)
        thread.start()
        return self

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()


def _peer_count(node) -> int:
    p2p = getattr(node, "p2p_server", None)
    return len(p2p.peers) if p2p else 0


def _sha3(data) -> str:
    from ..crypto.keccak import keccak256

    if not isinstance(data, str):
        raise RpcError(-32602, "web3_sha3 expects a hex string")
    try:
        raw = bytes.fromhex(data.removeprefix("0x"))
    except ValueError as e:
        raise RpcError(-32602, f"invalid hex data: {e}")
    return "0x" + keccak256(raw).hex()


def _err(rid, code, message, data=None):
    error = {"code": code, "message": message}
    if data is not None:
        error["data"] = data
    return {"jsonrpc": "2.0", "id": rid, "error": error}


def _get_nonce_fn(node):
    head = node.store.get_canonical_block(node.store.latest_number())

    def get_nonce(sender: bytes) -> int:
        acct = node.store.account_state(head.header.state_root, sender)
        return acct.nonce if acct else 0

    return get_nonce


def _txpool_content(node):
    from .serializers import tx_to_json

    pending, queued = node.mempool.split(_get_nonce_fn(node))

    def fmt(part):
        return {
            "0x" + sender.hex(): {
                str(nonce): tx_to_json(tx) for nonce, tx in queue.items()
            } for sender, queue in part.items()
        }

    return {"pending": fmt(pending), "queued": fmt(queued)}


def _txpool_status(node):
    counts = node.mempool.status(_get_nonce_fn(node))
    return {"pending": hex(counts["pending"]),
            "queued": hex(counts["queued"])}


def _admin_node_info(node):
    """admin_nodeInfo (reference: admin namespace, rpc.rs)."""
    p2p = getattr(node, "p2p_server", None)
    genesis = node.store.meta.get("genesis")
    info = {
        "name": f"{CLIENT_NAME}/{CLIENT_VERSION}",
        "protocols": {
            "eth": {
                "network": node.config.chain_id,
                "genesis": "0x" + genesis.hex() if genesis else None,
            },
        },
    }
    if p2p is not None:
        info["enode"] = (f"enode://{p2p.pub.hex()}"
                         f"@{p2p.host}:{p2p.port}")
        info["listenAddr"] = f"{p2p.host}:{p2p.port}"
        info["id"] = p2p.pub.hex()
    return info


def _admin_peers(node):
    p2p = getattr(node, "p2p_server", None)
    if p2p is None:
        return []
    out = []
    for peer in list(p2p.peers):
        try:
            host, port = peer.sock.getpeername()[:2]
        except OSError:
            host, port = "", 0
        entry = {
            "id": bytes(peer.remote_pub).hex(),
            "network": {"remoteAddress": f"{host}:{port}"},
            "score": getattr(peer, "score", 0),
        }
        status = peer.remote_status
        if status is not None:
            entry["protocols"] = {"eth": {
                "version": status.version,
                "head": "0x" + status.head_hash.hex(),
            }}
        out.append(entry)
    return out


def _produce(node):
    block = node.produce_block()
    return "0x" + block.hash.hex()


def _rollup(node):
    seq = getattr(node, "sequencer", None)
    if seq is None:
        raise RpcError(-32000, "node is not running an L2 sequencer")
    return seq


def _batch_json(batch, rollup):
    from .serializers import hb, hx

    with rollup.lock:  # a half-applied set_committed must not leak out
        return {
            "number": hx(batch.number),
            "firstBlock": hx(batch.first_block),
            "lastBlock": hx(batch.last_block),
            "stateRoot": hb(batch.state_root),
            "commitment": hb(batch.commitment),
            "committed": batch.committed,
            "verified": batch.verified,
        }


def _latest_batch(node):
    seq = _rollup(node)
    n = seq.rollup.latest_batch_number()
    batch = seq.rollup.get_batch(n)
    return _batch_json(batch, seq.rollup) if batch else None


def _get_batch(node, n):
    from .serializers import parse_quantity

    seq = _rollup(node)
    batch = seq.rollup.get_batch(parse_quantity(n))
    return _batch_json(batch, seq.rollup) if batch else None


def _find_batch_for_block(seq, block_number):
    with seq.rollup.lock:
        for n in sorted(seq.rollup.batches):
            b = seq.rollup.batches[n]
            if b.first_block <= block_number <= b.last_block:
                return b
    return None


def _batch_by_block(node, n):
    """ethrex_batchNumberByBlock: which batch carries an L2 block."""
    from .serializers import hx, parse_quantity

    seq = _rollup(node)
    batch = _find_batch_for_block(seq, parse_quantity(n))
    return hx(batch.number) if batch else None


def _l1_message_proof(node, tx_hash_hex):
    """ethrex_getL1MessageProof: the withdrawal claim data for a tx —
    its batch, message index, leaf hash and Merkle path against the
    batch's message root (reference:
    crates/l2/networking/rpc/l2/messages.rs GetL1MessageProof)."""
    from ..l2.messages import collect_messages, message_proof, message_root
    from .serializers import hb, hx, parse_bytes

    seq = _rollup(node)
    tx_hash = parse_bytes(tx_hash_hex)
    loc = node.store.tx_index.get(tx_hash)
    if loc is None:
        return None
    header = node.store.get_header(loc[0])
    if header is None:
        return None
    block_number = header.number
    batch = _find_batch_for_block(seq, block_number)
    if batch is None:
        return None
    blocks = [node.store.get_canonical_block(n)
              for n in range(batch.first_block, batch.last_block + 1)]
    if any(b is None for b in blocks):
        return None
    receipts = [node.store.get_receipts(b.hash) for b in blocks]
    if any(r is None for r in receipts):
        # a message set built without the success filter would diverge
        # from the committed root and serve an unclaimable proof
        raise RpcError(-32000, "missing receipts for a batched block")
    messages = collect_messages(blocks, receipts)
    for idx, msg in enumerate(messages):
        if msg.tx_hash == tx_hash:
            return {
                "batchNumber": hx(batch.number),
                "messageId": hx(idx),
                "messageHash": hb(msg.leaf()),
                "merkleProof": [hb(p)
                                for p in message_proof(messages, idx)],
                "messageRoot": hb(message_root(messages)),
                "verified": batch.verified,
            }
    return None


def _require_admin(server):
    """Admin control methods live behind an explicit opt-in: the public
    unauthenticated RPC must not let any client halt batch commitment
    (the reference keeps these on a dedicated admin listener,
    admin_server.rs; here `RpcServer(admin=True)` / --l2.admin)."""
    if not getattr(server, "admin_enabled", False):
        raise RpcError(-32601, "admin methods are disabled "
                               "(start with admin enabled)")


def _admin_committer(server, node, start: bool, delay=0):
    """ethrex_adminStart/StopCommitter: pause/resume the L1 committer
    actor, optionally delayed (reference: admin_server.rs
    /committer/start/{delay} and /committer/stop)."""
    from .serializers import parse_quantity

    _require_admin(server)
    seq = _rollup(node)
    name = "commit_next_batch"
    if start:
        seq.resume_actor(name, float(parse_quantity(delay)
                                     if isinstance(delay, str) else delay))
    else:
        seq.pause_actor(name)
    return {"committer": "running" if start else "paused"}


def _admin_stop_at(server, node, n):
    """ethrex_adminSetStopAtBatch: the committer stops producing batch
    checkpoints past this number; null clears the cap
    (admin_server.rs set_sequencer_stop_at)."""
    from .serializers import hx, parse_quantity

    _require_admin(server)
    seq = _rollup(node)
    seq.stop_at_batch = None if n is None else parse_quantity(n)
    return {"stopAtBatch": None if seq.stop_at_batch is None
            else hx(seq.stop_at_batch)}


def _trace_limit(limit) -> int:
    """ethrex_trace_* limit param: JSON int or 0x-quantity, default 20."""
    if limit is None:
        return 20
    if isinstance(limit, str):
        from .serializers import parse_quantity

        return parse_quantity(limit)
    return int(limit)


def _alerts(node):
    """ethrex_alerts: alert-engine state, degrading to a disabled stub
    on nodes that never attached an engine (L1-only / older nodes)."""
    eng = getattr(node, "alerts", None)
    if eng is None:
        return {"enabled": False, "rules": [], "active": [], "recent": []}
    out = {"enabled": True}
    out.update(eng.to_json())
    return out


def _perf(node):
    """ethrex_perf: stage-attribution tree + roofline report + live
    throughput gauges.  The profiler and roofline registries are
    process-global, so this answers on every node flavor; sections that
    fail (or never populated — e.g. roofline on an L1-only node that
    never compiled a prover kernel) degrade to stubs, not errors."""
    out = {"enabled": True}
    try:
        from ..perf import profiler
        out["profiler"] = profiler.PROFILER.tree()
    except Exception as exc:  # noqa: BLE001 — telemetry endpoint
        out["profiler"] = {"error": f"{type(exc).__name__}: {exc}"}
    try:
        from ..perf import roofline
        out["roofline"] = roofline.ROOFLINE.report()
    except Exception as exc:  # noqa: BLE001 — telemetry endpoint
        out["roofline"] = {"error": f"{type(exc).__name__}: {exc}"}
    try:
        from ..utils.metrics import METRICS
        with METRICS.lock:
            gauges = dict(METRICS.gauges)
        out["throughput"] = {
            name: gauges.get(name)
            for name in ("l1_import_mgas_per_sec",
                         "prover_trace_cells_per_sec",
                         "proofs_per_hour")
        }
        out["mesh"] = {
            "devices": gauges.get("prover_mesh_devices"),
            "vmCircuitsParallel":
                gauges.get("prover_vm_circuits_parallel"),
        }
    except Exception as exc:  # noqa: BLE001 — telemetry endpoint
        out["throughput"] = {"error": f"{type(exc).__name__}: {exc}"}
    try:
        from ..utils import exec_cache
        out["executableCache"] = exec_cache.runtime_stats()
    except Exception as exc:  # noqa: BLE001 — telemetry endpoint
        out["executableCache"] = {
            "error": f"{type(exc).__name__}: {exc}"}
    return out


def _debug_snapshot(node):
    """ethrex_debug_snapshot: return a flight-recorder bundle, and
    persist it when --debug-snapshot-dir configured a destination."""
    from ..utils import snapshot

    bundle = snapshot.collect(node, reason="rpc")
    path = snapshot.write(node, reason="rpc", bundle=bundle)
    if path is not None:
        bundle["path"] = path
    return bundle


def _rpc_traffic_json() -> dict:
    """Request-lifecycle counters/gauges for ethrex_health: connection
    churn, in-flight work, byte totals and the configured backlog —
    read straight from the global registry."""
    with METRICS.lock:
        c = dict(METRICS.counters)
        g = dict(METRICS.gauges)
    return {
        "accepted": int(c.get("rpc_connections_accepted_total", 0)),
        "resets": int(c.get("rpc_connections_reset_total", 0)),
        "eof": int(c.get("rpc_connections_eof_total", 0)),
        "inflight": int(g.get("rpc_inflight_requests", 0)),
        "listenBacklog": g.get("rpc_listen_backlog"),
        "requestBytes": int(c.get("rpc_request_bytes_total", 0)),
        "responseBytes": int(c.get("rpc_response_bytes_total", 0)),
        "slowRequests": int(c.get("rpc_slow_requests_total", 0)),
        "shed": int(c.get("rpc_requests_shed_total", 0)),
        "shedLevel": int(g.get("rpc_shed_level", 0)),
        "wsConnections": int(g.get("ws_connections", 0)),
        "wsNotifications": int(c.get("ws_notifications_total", 0)),
        "wsSendFailures": int(c.get("ws_send_failures_total", 0)),
        "wsNotificationsDropped":
            int(c.get("ws_notifications_dropped_total", 0)),
        "wsSlowConsumerDisconnects":
            int(c.get("ws_slow_consumer_disconnects_total", 0)),
    }


def _mempool_util(node) -> float | None:
    """Mempool fill fraction for the overload controller's shed-level
    feedback; None (never sheds) when the node has no mempool."""
    mempool = getattr(node, "mempool", None)
    return mempool.utilization() if mempool is not None else None


def _health(node):
    out = {
        "head": node.store.latest_number(),
        "mempool": len(node.mempool),
        "mempoolFlow": node.mempool.stats_json(),
        "rpc": _rpc_traffic_json(),
        "peers": _peer_count(node),
        "tracing": {"bufferedTraces": len(TRACER),
                    "droppedTraces": TRACER.dropped},
    }
    overload = getattr(node, "rpc_overload", None)
    if overload is not None:
        out["rpc"]["overload"] = overload.to_json()
    alerts = getattr(node, "alerts", None)
    if alerts is not None:
        active = alerts.active()
        out["alerts"] = {
            "firing": len(active),
            "page": sum(1 for a in active if a["severity"] == "page"),
            "warn": sum(1 for a in active if a["severity"] == "warn"),
            "active": [a["name"] for a in active],
            "transitions": alerts.transitions_total,
        }
    telemetry = getattr(node, "telemetry", None)
    if telemetry is not None:
        out["telemetry"] = {"samples": len(telemetry.samples),
                            "samplerRunning": telemetry.running(),
                            "samplerErrors": telemetry.sampler_errors}
    sd = getattr(node, "shutdown", None)
    if sd is not None:
        out["shutdown"] = {"phase": sd.phase,
                           "durationSeconds": sd.duration}
    try:
        from ..perf import profiler, roofline

        rep = roofline.ROOFLINE.report()
        tree = profiler.PROFILER.tree()
        kernels = rep.get("kernels") or []
        utils = [k["utilizationVsPeak"] for k in kernels
                 if k.get("utilizationVsPeak") is not None]
        from ..crypto import native_secp256k1

        out["perf"] = {
            "componentsProfiled": sorted(tree.get("components", {})),
            "kernelsProfiled": len(kernels),
            "maxUtilizationVsPeak": max(utils) if utils else None,
            # which sender-recovery engine is live: the native C engine
            # or the pure-Python fallback (docs/PERFORMANCE.md)
            "nativeSecp256k1": native_secp256k1.available(),
        }
        from ..utils import exec_cache

        cache = exec_cache.runtime_stats()
        # cold-start posture: are AOT kernels hydrating from disk or
        # being recompiled? (docs/PERFORMANCE.md "Cold start")
        out["perf"]["executableCache"] = {
            k: cache.get(k)
            for k in ("hits", "misses", "errors", "entries", "enabled")}
    except Exception:  # noqa: BLE001 — health must answer regardless
        pass
    seq = getattr(node, "sequencer", None)
    if seq is not None:
        from ..storage.persistent import storage_stats
        from ..utils import shutdown as _shutdown

        stats = storage_stats()
        out["l2"] = {
            "latestBatch": seq.rollup.latest_batch_number(),
            "lastBatchedBlock": seq.last_batched_block,
            "pendingPrivileged": len(seq.pending_privileged),
            "actors": {name: st.to_json()
                       for name, st in seq.health.items()},
            # admin state: a deliberately paused actor must be
            # distinguishable from a stuck one (review finding)
            "paused": sorted(seq.paused),
            "resumeAt": dict(seq._resume_at),
            "stopAtBatch": seq.stop_at_batch,
            "fatal": list(seq.fatal) if seq.fatal else None,
            # prover pipeline resilience: lease/reassignment counters and
            # the poison-batch quarantine (docs/PROVER_RESILIENCE.md);
            # the fleet scheduler state rides inside under "scheduler"
            "prover": seq.coordinator.stats_json(),
            # recursive aggregation pipeline state (docs/AGGREGATION.md)
            "aggregation": {
                "enabled": seq.cfg.aggregation_enabled,
                **seq.aggregator.stats_json(),
            },
            # L1 settlement resilience: reorg/recommit/adoption counters
            # and the recommit backlog (docs/L1_SETTLEMENT_RESILIENCE.md)
            "l1": {
                "reorgs": seq.reorgs_total,
                "recommitted": seq.recommits_total,
                "adoptedCommits": seq.commits_adopted_total,
                "rebuiltBatches": seq.rebuilt_batches_total,
                "recommitQueue": sorted(seq._recommit_queue),
                "confirmationDepth": seq.cfg.l1_confirmation_depth,
            },
            # storage resilience: corruption/rebuild/journal counters and
            # the last drain duration (docs/STORAGE_RESILIENCE.md)
            "store": {
                "corruptRecords": stats["corrupt_records"],
                "rebuiltRecords": stats["rebuilt_records"],
                "journalReplays": stats["journal_replays"],
                "journalDiscards": stats["journal_discards"],
                "lastShutdownSeconds": _shutdown.LAST_DURATION,
            },
        }
    return out
