"""WebSocket JSON-RPC transport + eth_subscribe push subscriptions.

The reference serves subscriptions over websockets
(crates/networking/rpc subscription_manager; newHeads / logs /
newPendingTransactions).  This is a dependency-free RFC 6455 server:
handshake, masked client frames, text frames out, ping/pong, close.  All
regular JSON-RPC methods route through the owning RpcServer's method
table; eth_subscribe/eth_unsubscribe manage per-connection subscriptions
pushed from the node's block and mempool hooks.

Slow-consumer protection (docs/OVERLOAD.md): notifications are never
sent from the fan-out loop.  Each connection owns a BOUNDED send queue
drained by a dedicated writer thread, so one stalled subscriber cannot
block delivery to healthy ones.  When a consumer's queue is full its
notifications are dropped (counted), and a consumer that STAYS full
past the slow-consumer deadline is disconnected (counted in
ws_slow_consumer_disconnects_total) instead of holding a queue of stale
heads forever.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import queue
import socket
import struct
import threading
import time

from ..utils.metrics import (record_ws_accept, record_ws_connections,
                             record_ws_notification,
                             record_ws_notification_drop,
                             record_ws_send_failure,
                             record_ws_slow_consumer_disconnect)

_GUID = b"258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

# per-connection notification queue bound + how long a consumer may
# stay full before it is disconnected (env-tunable; docs/OVERLOAD.md)
NOTIFY_QUEUE_SIZE = int(os.environ.get("ETHREX_WS_NOTIFY_QUEUE", "256"))
SLOW_CONSUMER_DEADLINE = float(
    os.environ.get("ETHREX_WS_SLOW_DEADLINE", "5.0"))

OP_TEXT = 0x1
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA

# RFC 6455 §10.4: cap the total message size so a client-declared 2^64-1
# length can't drive unbounded buffering; 8 MiB covers any JSON-RPC batch.
MAX_MESSAGE_BYTES = 8 * 1024 * 1024


class ProtocolError(ConnectionError):
    """Client violated RFC 6455 (oversized message / unmasked frame)."""

    def __init__(self, close_code: int, reason: str):
        super().__init__(reason)
        self.close_code = close_code


def _accept_key(key: str) -> str:
    digest = hashlib.sha1(key.encode() + _GUID).digest()
    return base64.b64encode(digest).decode()


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def read_frame(sock: socket.socket, require_mask: bool = False,
               on_control=None) -> tuple[int, bytes]:
    """Returns (opcode, payload) of one (possibly fragmented) message.

    Servers pass require_mask=True: RFC 6455 §5.1 requires client→server
    frames to be masked and the connection failed otherwise.

    Control frames may be interleaved between fragments of a data message
    (RFC 6455 §5.4); `on_control(op, data) -> bool` handles them inline
    (True = consumed, keep reading).  Unconsumed control frames are
    returned directly — mid-fragment that abandons the partial data
    message, which only happens for CLOSE."""
    payload = b""
    opcode = None
    while True:
        h0, h1 = _recv_exact(sock, 2)
        fin = h0 & 0x80
        op = h0 & 0x0F
        masked = h1 & 0x80
        length = h1 & 0x7F
        if length == 126:
            (length,) = struct.unpack(">H", _recv_exact(sock, 2))
        elif length == 127:
            (length,) = struct.unpack(">Q", _recv_exact(sock, 8))
        if require_mask and not masked:
            # RFC 6455 §5.1: a server MUST fail the connection on
            # unmasked client frames.
            raise ProtocolError(1002, "unmasked client frame")
        if length + len(payload) > MAX_MESSAGE_BYTES:
            raise ProtocolError(1009, "message too big")
        mask = _recv_exact(sock, 4) if masked else b"\x00" * 4
        data = bytearray(_recv_exact(sock, length))
        if masked:
            for i in range(len(data)):
                data[i] ^= mask[i % 4]
        if op & 0x8:
            # control frame: never fragmented (§5.5), must not interrupt
            # the reassembly buffer of an in-flight data message
            if not fin or length > 125:
                raise ProtocolError(1002, "bad control frame")
            if on_control is not None and on_control(op, bytes(data)):
                continue
            return op, bytes(data)
        if op != 0:
            opcode = op
        payload += bytes(data)
        if fin:
            return opcode, payload


def make_frame(opcode: int, payload: bytes) -> bytes:
    header = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        header += bytes([n])
    elif n < (1 << 16):
        header += bytes([126]) + struct.pack(">H", n)
    else:
        header += bytes([127]) + struct.pack(">Q", n)
    return header + payload


class _Subscription:
    def __init__(self, sid: str, kind: str, params: dict | None):
        self.sid = sid
        self.kind = kind
        self.params = params or {}


class WsConnection:
    def __init__(self, server: "WsServer", sock: socket.socket):
        self.server = server
        self.sock = sock
        self.subs: dict[str, _Subscription] = {}
        self.send_lock = threading.Lock()
        self.alive = True
        # per-connection lifecycle counters (surfaced by the fan-out
        # tests and useful when debugging a lagging subscriber)
        self.notifications_sent = 0
        self.send_failures = 0
        self.notifications_dropped = 0
        # bounded notification queue + dedicated writer: the fan-out
        # loop only ever enqueues (non-blocking), so a stalled consumer
        # cannot block delivery to healthy subscribers
        self._sendq: queue.Queue = queue.Queue(
            maxsize=getattr(server, "notify_queue_size",
                            NOTIFY_QUEUE_SIZE))
        self._full_since: float | None = None
        self._writer = threading.Thread(target=self._writer_loop,
                                        daemon=True)
        self._writer.start()

    def send_json(self, obj) -> bool:
        data = json.dumps(obj).encode()
        try:
            with self.send_lock:
                self.sock.sendall(make_frame(OP_TEXT, data))
            return True
        except OSError:
            self.alive = False
            return False

    def _writer_loop(self):
        """Drain the notification queue in order; counters tick at the
        actual send so notifications_sent means delivered-to-socket."""
        while True:
            frame = self._sendq.get()
            if frame is None:
                return
            try:
                with self.send_lock:
                    self.sock.sendall(frame)
            except OSError:
                self.alive = False
                self.send_failures += 1
                record_ws_send_failure()
                return
            self.notifications_sent += 1
            record_ws_notification()

    def notify(self, sid: str, result) -> bool:
        frame = make_frame(OP_TEXT, json.dumps({
            "jsonrpc": "2.0", "method": "eth_subscription",
            "params": {"subscription": sid, "result": result},
        }).encode())
        try:
            self._sendq.put_nowait(frame)
        except queue.Full:
            now = time.monotonic()
            if self._full_since is None:
                self._full_since = now
            self.notifications_dropped += 1
            record_ws_notification_drop()
            deadline = getattr(self.server, "slow_consumer_deadline",
                               SLOW_CONSUMER_DEADLINE)
            if now - self._full_since >= deadline:
                self._disconnect_slow()
            return False
        self._full_since = None
        return True

    def _disconnect_slow(self):
        """The consumer stayed full past the deadline: close it rather
        than serve an ever-staler backlog (docs/OVERLOAD.md)."""
        if not self.alive:
            return
        self.alive = False
        record_ws_slow_consumer_disconnect()
        self.server.connections.discard(self)
        record_ws_connections(len(self.server.connections))
        try:
            self.sock.close()
        except OSError:
            pass

    def handle_request(self, req: dict):
        method = req.get("method")
        rid = req.get("id")
        params = req.get("params", [])
        if method == "eth_subscribe":
            kind = params[0]
            if kind not in ("newHeads", "newPendingTransactions", "logs"):
                return {"jsonrpc": "2.0", "id": rid,
                        "error": {"code": -32602,
                                  "message": f"unsupported: {kind}"}}
            import secrets

            sid = "0x" + secrets.token_hex(16)
            opts = params[1] if len(params) > 1 else None
            self.subs[sid] = _Subscription(sid, kind, opts)
            return {"jsonrpc": "2.0", "id": rid, "result": sid}
        if method == "eth_unsubscribe":
            found = self.subs.pop(params[0], None) is not None
            return {"jsonrpc": "2.0", "id": rid, "result": found}
        return self.server.rpc.handle(req)

    def _on_control(self, op: int, data: bytes) -> bool:
        if op == OP_PING:
            with self.send_lock:
                self.sock.sendall(make_frame(OP_PONG, data))
            return True
        if op == OP_PONG:
            return True
        return False  # CLOSE: surface to the main loop

    def run(self):
        try:
            while self.alive:
                opcode, payload = read_frame(self.sock, require_mask=True,
                                             on_control=self._on_control)
                if opcode == OP_CLOSE:
                    with self.send_lock:
                        self.sock.sendall(make_frame(OP_CLOSE, b""))
                    break
                if opcode != OP_TEXT:
                    continue
                try:
                    req = json.loads(payload)
                except json.JSONDecodeError:
                    self.send_json({"jsonrpc": "2.0", "id": None,
                                    "error": {"code": -32700,
                                              "message": "parse error"}})
                    continue
                if isinstance(req, list):
                    self.send_json([self.handle_request(r) for r in req])
                else:
                    self.send_json(self.handle_request(req))
        except ProtocolError as exc:
            try:
                with self.send_lock:
                    self.sock.sendall(make_frame(
                        OP_CLOSE, struct.pack(">H", exc.close_code)))
            except OSError:
                pass
        except (ConnectionError, OSError):
            pass
        finally:
            self.alive = False
            self.server.connections.discard(self)
            record_ws_connections(len(self.server.connections))
            try:
                self.sock.close()
            except OSError:
                pass
            # wake the writer so the thread exits; a full queue means
            # the writer is mid-send and will exit on the closed socket
            try:
                self._sendq.put_nowait(None)
            except queue.Full:
                pass


class WsServer:
    """WebSocket endpoint bound to an RpcServer's method table."""

    def __init__(self, rpc_server, host: str = "127.0.0.1", port: int = 0,
                 backlog: int | None = None,
                 notify_queue_size: int = NOTIFY_QUEUE_SIZE,
                 slow_consumer_deadline: float = SLOW_CONSUMER_DEADLINE):
        self.rpc = rpc_server
        self.node = rpc_server.node
        self.notify_queue_size = notify_queue_size
        self.slow_consumer_deadline = slow_consumer_deadline
        self.listener = socket.create_server(
            (host, port), backlog=backlog)
        self.host, self.port = self.listener.getsockname()[:2]
        self.connections: set[WsConnection] = set()
        self._stop = threading.Event()
        # push hooks
        self.node.block_listeners.append(self._on_block)
        self.node.mempool.on_add.append(self._on_pending_tx)

    # -- push paths --------------------------------------------------------
    def _on_block(self, block):
        from .serializers import header_to_json

        head_json = None
        logs_cache = None
        for conn in list(self.connections):
            for sub in list(conn.subs.values()):
                if sub.kind == "newHeads":
                    if head_json is None:
                        head_json = header_to_json(block.header, block.hash)
                    conn.notify(sub.sid, head_json)
                elif sub.kind == "logs":
                    if logs_cache is None:
                        logs_cache = self._block_logs(block)
                    for log_json in logs_cache:
                        if _log_matches(log_json, sub.params):
                            conn.notify(sub.sid, log_json)

    def _block_logs(self, block) -> list[dict]:
        receipts = self.node.store.get_receipts(block.hash) or []
        out = []
        log_index = 0
        for tx_index, (tx, receipt) in enumerate(
                zip(block.body.transactions, receipts)):
            for log in receipt.logs:
                out.append({
                    "address": "0x" + log.address.hex(),
                    "topics": ["0x" + bytes(t).hex() for t in log.topics],
                    "data": "0x" + log.data.hex(),
                    "blockNumber": hex(block.header.number),
                    "blockHash": "0x" + block.hash.hex(),
                    "transactionHash": "0x" + tx.hash.hex(),
                    "transactionIndex": hex(tx_index),
                    "logIndex": hex(log_index),
                    "removed": False,
                })
                log_index += 1
        return out

    def _on_pending_tx(self, tx_hash: bytes):
        for conn in list(self.connections):
            for sub in list(conn.subs.values()):
                if sub.kind == "newPendingTransactions":
                    conn.notify(sub.sid, "0x" + tx_hash.hex())

    # -- accept loop -------------------------------------------------------
    def _handshake(self, sock: socket.socket) -> bool:
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = sock.recv(4096)
            if not chunk:
                return False
            data += chunk
        headers = {}
        for line in data.split(b"\r\n")[1:]:
            if b":" in line:
                k, v = line.split(b":", 1)
                headers[k.strip().lower().decode()] = v.strip().decode()
        key = headers.get("sec-websocket-key")
        if not key or "websocket" not in \
                headers.get("upgrade", "").lower():
            sock.sendall(b"HTTP/1.1 400 Bad Request\r\n\r\n")
            return False
        sock.sendall(
            b"HTTP/1.1 101 Switching Protocols\r\n"
            b"Upgrade: websocket\r\n"
            b"Connection: Upgrade\r\n"
            b"Sec-WebSocket-Accept: " + _accept_key(key).encode()
            + b"\r\n\r\n")
        return True

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                sock, _ = self.listener.accept()
            except OSError:
                break
            try:
                if not self._handshake(sock):
                    sock.close()
                    continue
            except OSError:
                continue
            conn = WsConnection(self, sock)
            self.connections.add(conn)
            record_ws_accept()
            record_ws_connections(len(self.connections))
            threading.Thread(target=conn.run, daemon=True).start()

    def start(self):
        threading.Thread(target=self._accept_loop, daemon=True).start()
        return self

    def stop(self):
        self._stop.set()
        try:
            self.listener.close()
        except OSError:
            pass
        for conn in list(self.connections):
            try:
                conn.sock.close()
            except OSError:
                pass


def _log_matches(log_json: dict, params: dict) -> bool:
    addr = params.get("address")
    if addr:
        addrs = [addr] if isinstance(addr, str) else list(addr)
        if log_json["address"].lower() not in \
                (a.lower() for a in addrs):
            return False
    topics = params.get("topics") or []
    have = log_json["topics"]
    for i, want in enumerate(topics):
        if want is None:
            continue
        if i >= len(have):
            return False
        options = [want] if isinstance(want, str) else list(want)
        if have[i].lower() not in (o.lower() for o in options):
            return False
    return True
