"""WebSocket JSON-RPC transport + eth_subscribe push subscriptions.

The reference serves subscriptions over websockets
(crates/networking/rpc subscription_manager; newHeads / logs /
newPendingTransactions).  This is a dependency-free RFC 6455 server:
handshake, masked client frames, text frames out, ping/pong, close.
Framing lives in a sans-IO generator (`_parse_message`) driven by two
interchangeable IO layers — the blocking `read_frame` (kept for test
clients and tooling) and the asyncio reader used by the server.  All
regular JSON-RPC methods route through the owning RpcServer's executor
pool; eth_subscribe/eth_unsubscribe manage per-connection subscriptions
pushed from the node's block and mempool hooks.

Like the HTTP front door, the server side is a single event loop
(rpc/aio.LoopThread; SEDA — Welsh et al., SOSP 2001; PAPERS.md): one
reader task and one writer task per connection instead of two threads.

Slow-consumer protection (docs/OVERLOAD.md): notifications are never
sent from the fan-out loop.  Each connection owns a BOUNDED send queue
drained by its writer task, so one stalled subscriber cannot block
delivery to healthy ones.  When a consumer's queue is full its
notifications are dropped (counted), and a consumer that STAYS full
past the slow-consumer deadline is disconnected (counted in
ws_slow_consumer_disconnects_total) instead of holding a queue of stale
heads forever.  A `WsConnection` built without a loop (direct
construction over a raw socket, as the overload tests do) falls back to
a writer thread with identical queue/drop/deadline semantics.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import json
import os
import queue
import socket
import struct
import threading
import time

from ..utils.metrics import (record_ws_accept, record_ws_connections,
                             record_ws_notification,
                             record_ws_notification_drop,
                             record_ws_send_failure,
                             record_ws_slow_consumer_disconnect)

_GUID = b"258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

# per-connection notification queue bound + how long a consumer may
# stay full before it is disconnected (env-tunable; docs/OVERLOAD.md)
NOTIFY_QUEUE_SIZE = int(os.environ.get("ETHREX_WS_NOTIFY_QUEUE", "256"))
SLOW_CONSUMER_DEADLINE = float(
    os.environ.get("ETHREX_WS_SLOW_DEADLINE", "5.0"))

OP_TEXT = 0x1
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA

# RFC 6455 §10.4: cap the total message size so a client-declared 2^64-1
# length can't drive unbounded buffering; 8 MiB covers any JSON-RPC batch.
MAX_MESSAGE_BYTES = 8 * 1024 * 1024


class ProtocolError(ConnectionError):
    """Client violated RFC 6455 (oversized message / unmasked frame)."""

    def __init__(self, close_code: int, reason: str):
        super().__init__(reason)
        self.close_code = close_code


def _accept_key(key: str) -> str:
    digest = hashlib.sha1(key.encode() + _GUID).digest()
    return base64.b64encode(digest).decode()


# -- sans-IO framing ---------------------------------------------------------


def _parse_message(require_mask: bool = False):
    """Sans-IO RFC 6455 message parser (one generator per message).

    Yields ("need", n) to request exactly n bytes from the driver, and
    ("control", op, data) when a control frame interleaves a fragmented
    data message — the driver sends back True when it consumed the
    control frame (ping/pong) or False to abandon the message (close).
    Returns (opcode, payload) of the completed message via
    StopIteration.value.  Both the blocking `read_frame` and the async
    reader drive this same generator, so the two transports cannot
    drift on framing rules."""
    payload = b""
    opcode = None
    while True:
        h0, h1 = (yield ("need", 2))
        fin = h0 & 0x80
        op = h0 & 0x0F
        masked = h1 & 0x80
        length = h1 & 0x7F
        if length == 126:
            (length,) = struct.unpack(">H", (yield ("need", 2)))
        elif length == 127:
            (length,) = struct.unpack(">Q", (yield ("need", 8)))
        if require_mask and not masked:
            # RFC 6455 §5.1: a server MUST fail the connection on
            # unmasked client frames.
            raise ProtocolError(1002, "unmasked client frame")
        if length + len(payload) > MAX_MESSAGE_BYTES:
            raise ProtocolError(1009, "message too big")
        mask = (yield ("need", 4)) if masked else b"\x00" * 4
        data = bytearray((yield ("need", length)) if length else b"")
        if masked:
            for i in range(len(data)):
                data[i] ^= mask[i % 4]
        if op & 0x8:
            # control frame: never fragmented (§5.5), must not interrupt
            # the reassembly buffer of an in-flight data message
            if not fin or length > 125:
                raise ProtocolError(1002, "bad control frame")
            consumed = yield ("control", op, bytes(data))
            if consumed:
                continue
            return op, bytes(data)
        if op != 0:
            opcode = op
        payload += bytes(data)
        if fin:
            return opcode, payload


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def read_frame(sock: socket.socket, require_mask: bool = False,
               on_control=None) -> tuple[int, bytes]:
    """Blocking driver for `_parse_message` (test clients, tooling).

    Returns (opcode, payload) of one (possibly fragmented) message.
    `on_control(op, data) -> bool` handles interleaved control frames
    inline (True = consumed, keep reading); unconsumed control frames
    are returned directly — which only happens for CLOSE."""
    gen = _parse_message(require_mask)
    event = gen.send(None)
    while True:
        if event[0] == "need":
            reply = _recv_exact(sock, event[1]) if event[1] else b""
        else:
            reply = bool(on_control is not None
                         and on_control(event[1], event[2]))
        try:
            event = gen.send(reply)
        except StopIteration as stop:
            return stop.value


async def read_frame_async(reader: asyncio.StreamReader,
                           require_mask: bool = False,
                           on_control=None) -> tuple[int, bytes]:
    """Async driver for `_parse_message`; `on_control` is awaited (it
    may write a pong)."""
    gen = _parse_message(require_mask)
    event = gen.send(None)
    while True:
        if event[0] == "need":
            try:
                reply = await reader.readexactly(event[1]) \
                    if event[1] else b""
            except asyncio.IncompleteReadError:
                raise ConnectionError("peer closed") from None
        else:
            reply = False
            if on_control is not None:
                reply = bool(await on_control(event[1], event[2]))
        try:
            event = gen.send(reply)
        except StopIteration as stop:
            return stop.value


def make_frame(opcode: int, payload: bytes) -> bytes:
    header = bytes([0x80 | opcode])
    n = len(payload)
    if n < 126:
        header += bytes([n])
    elif n < (1 << 16):
        header += bytes([126]) + struct.pack(">H", n)
    else:
        header += bytes([127]) + struct.pack(">Q", n)
    return header + payload


def _parse_handshake(data: bytes) -> str | None:
    """Extract the Sec-WebSocket-Key from an upgrade request, or None
    when the request is not a websocket upgrade."""
    headers = {}
    for line in data.split(b"\r\n")[1:]:
        if b":" in line:
            k, v = line.split(b":", 1)
            headers[k.strip().lower().decode()] = v.strip().decode()
    key = headers.get("sec-websocket-key")
    if not key or "websocket" not in headers.get("upgrade", "").lower():
        return None
    return key


def _handshake_response(key: str) -> bytes:
    return (b"HTTP/1.1 101 Switching Protocols\r\n"
            b"Upgrade: websocket\r\n"
            b"Connection: Upgrade\r\n"
            b"Sec-WebSocket-Accept: " + _accept_key(key).encode()
            + b"\r\n\r\n")


class _Subscription:
    def __init__(self, sid: str, kind: str, params: dict | None):
        self.sid = sid
        self.kind = kind
        self.params = params or {}


class WsConnection:
    def __init__(self, server: "WsServer", sock: socket.socket,
                 reader: asyncio.StreamReader | None = None,
                 writer: asyncio.StreamWriter | None = None):
        self.server = server
        self.sock = sock
        self.reader = reader
        self.writer = writer
        self.subs: dict[str, _Subscription] = {}
        self.send_lock = threading.Lock()
        self.alive = True
        # per-connection lifecycle counters (surfaced by the fan-out
        # tests and useful when debugging a lagging subscriber)
        self.notifications_sent = 0
        self.send_failures = 0
        self.notifications_dropped = 0
        # bounded notification queue drained by ONE writer (task on the
        # server loop, or a fallback thread when constructed standalone
        # over a raw socket): the fan-out loop only ever enqueues
        # (non-blocking), so a stalled consumer cannot block delivery
        # to healthy subscribers
        self._sendq: queue.Queue = queue.Queue(
            maxsize=getattr(server, "notify_queue_size",
                            NOTIFY_QUEUE_SIZE))
        self._full_since: float | None = None
        self._loop = getattr(server, "loop", None) \
            if writer is not None else None
        self._wake: asyncio.Event | None = None
        self._writer_task: asyncio.Task | None = None
        if self._loop is None:
            self._writer = threading.Thread(target=self._writer_loop,
                                            daemon=True)
            self._writer.start()

    # -- send paths ---------------------------------------------------------
    def send_json(self, obj) -> bool:
        """Blocking send (standalone/thread mode only)."""
        data = json.dumps(obj).encode()
        try:
            with self.send_lock:
                self.sock.sendall(make_frame(OP_TEXT, data))
            return True
        except OSError:
            self.alive = False
            return False

    def _writer_loop(self):
        """Thread fallback: drain the notification queue in order;
        counters tick at the actual send so notifications_sent means
        delivered-to-socket."""
        while True:
            frame = self._sendq.get()
            if frame is None:
                return
            try:
                with self.send_lock:
                    self.sock.sendall(frame)
            except OSError:
                self.alive = False
                self.send_failures += 1
                record_ws_send_failure()
                return
            self.notifications_sent += 1
            record_ws_notification()

    async def _writer_loop_async(self):
        """Event-loop writer task: same queue, same counters; woken by
        call_soon_threadsafe from producer threads."""
        try:
            while True:
                try:
                    frame = self._sendq.get_nowait()
                except queue.Empty:
                    if not self.alive:
                        return
                    self._wake.clear()
                    await self._wake.wait()
                    continue
                if frame is None:
                    return
                self.writer.write(frame)
                await self.writer.drain()
                self.notifications_sent += 1
                record_ws_notification()
        except (ConnectionError, OSError):
            self.alive = False
            self.send_failures += 1
            record_ws_send_failure()
        except asyncio.CancelledError:
            pass

    def _wake_writer(self):
        loop = self._loop
        if loop is None or self._wake is None:
            return
        try:
            loop.call_soon_threadsafe(self._wake.set)
        except RuntimeError:
            pass  # loop already closed (server stopping)

    def notify(self, sid: str, result) -> bool:
        frame = make_frame(OP_TEXT, json.dumps({
            "jsonrpc": "2.0", "method": "eth_subscription",
            "params": {"subscription": sid, "result": result},
        }).encode())
        try:
            self._sendq.put_nowait(frame)
        except queue.Full:
            now = time.monotonic()
            if self._full_since is None:
                self._full_since = now
            self.notifications_dropped += 1
            record_ws_notification_drop()
            deadline = getattr(self.server, "slow_consumer_deadline",
                               SLOW_CONSUMER_DEADLINE)
            if now - self._full_since >= deadline:
                self._disconnect_slow()
            return False
        self._full_since = None
        self._wake_writer()
        return True

    def _disconnect_slow(self):
        """The consumer stayed full past the deadline: close it rather
        than serve an ever-staler backlog (docs/OVERLOAD.md)."""
        if not self.alive:
            return
        self.alive = False
        record_ws_slow_consumer_disconnect()
        self.server.connections.discard(self)
        record_ws_connections(len(self.server.connections))
        loop = self._loop
        if loop is not None:
            try:
                loop.call_soon_threadsafe(self._abort)
            except RuntimeError:
                pass
        try:
            self.sock.close()
        except OSError:
            pass

    def _abort(self):
        """Tear the transport down from the loop thread."""
        self.alive = False
        if self._wake is not None:
            self._wake.set()
        if self.writer is not None:
            try:
                transport = self.writer.transport
                if transport is not None:
                    transport.abort()
            except Exception:  # noqa: BLE001 — already closed
                pass

    # -- dispatch -----------------------------------------------------------
    def handle_request(self, req: dict):
        method = req.get("method")
        rid = req.get("id")
        params = req.get("params", [])
        if method == "eth_subscribe":
            kind = params[0]
            if kind not in ("newHeads", "newPendingTransactions", "logs"):
                return {"jsonrpc": "2.0", "id": rid,
                        "error": {"code": -32602,
                                  "message": f"unsupported: {kind}"}}
            import secrets

            sid = "0x" + secrets.token_hex(16)
            opts = params[1] if len(params) > 1 else None
            self.subs[sid] = _Subscription(sid, kind, opts)
            return {"jsonrpc": "2.0", "id": rid, "result": sid}
        if method == "eth_unsubscribe":
            found = self.subs.pop(params[0], None) is not None
            return {"jsonrpc": "2.0", "id": rid, "result": found}
        return self.server.rpc.handle(req)

    async def _handle_request_async(self, req):
        """Route one request: subscription management runs inline on
        the loop (it only touches this connection's dict); everything
        else crosses into the RpcServer's bounded executor so a slow
        handler never stalls the websocket loop."""
        if not isinstance(req, dict):
            return {"jsonrpc": "2.0", "id": None,
                    "error": {"code": -32600,
                              "message": "invalid request"}}
        if req.get("method") in ("eth_subscribe", "eth_unsubscribe"):
            return self.handle_request(req)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self.server.rpc._get_executor(), self.server.rpc.handle, req)

    async def _send_json_async(self, obj) -> None:
        self.writer.write(make_frame(OP_TEXT, json.dumps(obj).encode()))
        await self.writer.drain()

    async def _on_control_async(self, op: int, data: bytes) -> bool:
        if op == OP_PING:
            self.writer.write(make_frame(OP_PONG, data))
            await self.writer.drain()
            return True
        return op == OP_PONG  # CLOSE: surface to the reader loop

    async def run_async(self):
        """Reader task: one per connection on the server loop."""
        self._wake = asyncio.Event()
        self._writer_task = asyncio.ensure_future(
            self._writer_loop_async())
        try:
            while self.alive:
                opcode, payload = await read_frame_async(
                    self.reader, require_mask=True,
                    on_control=self._on_control_async)
                if opcode == OP_CLOSE:
                    self.writer.write(make_frame(OP_CLOSE, b""))
                    await self.writer.drain()
                    break
                if opcode != OP_TEXT:
                    continue
                try:
                    req = json.loads(payload)
                except json.JSONDecodeError:
                    await self._send_json_async(
                        {"jsonrpc": "2.0", "id": None,
                         "error": {"code": -32700,
                                   "message": "parse error"}})
                    continue
                if isinstance(req, list):
                    await self._send_json_async(list(await asyncio.gather(
                        *(self._handle_request_async(r) for r in req))))
                else:
                    await self._send_json_async(
                        await self._handle_request_async(req))
        except ProtocolError as exc:
            try:
                self.writer.write(make_frame(
                    OP_CLOSE, struct.pack(">H", exc.close_code)))
                await self.writer.drain()
            except (ConnectionError, OSError):
                pass
        except (ConnectionError, OSError):
            pass
        except asyncio.CancelledError:
            pass
        finally:
            self.alive = False
            self.server.connections.discard(self)
            record_ws_connections(len(self.server.connections))
            # wake the writer task so it exits, then tear down
            try:
                self._sendq.put_nowait(None)
            except queue.Full:
                pass
            self._wake.set()
            try:
                await asyncio.wait_for(self._writer_task, timeout=1.0)
            except (asyncio.TimeoutError, asyncio.CancelledError,
                    Exception):  # noqa: B014 — teardown best-effort
                self._writer_task.cancel()
            try:
                self.writer.close()
            except Exception:  # noqa: BLE001 — transport teardown
                pass

    # -- legacy blocking reader (standalone/thread mode) --------------------
    def _on_control(self, op: int, data: bytes) -> bool:
        if op == OP_PING:
            with self.send_lock:
                self.sock.sendall(make_frame(OP_PONG, data))
            return True
        if op == OP_PONG:
            return True
        return False  # CLOSE: surface to the main loop

    def run(self):
        try:
            while self.alive:
                opcode, payload = read_frame(self.sock, require_mask=True,
                                             on_control=self._on_control)
                if opcode == OP_CLOSE:
                    with self.send_lock:
                        self.sock.sendall(make_frame(OP_CLOSE, b""))
                    break
                if opcode != OP_TEXT:
                    continue
                try:
                    req = json.loads(payload)
                except json.JSONDecodeError:
                    self.send_json({"jsonrpc": "2.0", "id": None,
                                    "error": {"code": -32700,
                                              "message": "parse error"}})
                    continue
                if isinstance(req, list):
                    self.send_json([self.handle_request(r) for r in req])
                else:
                    self.send_json(self.handle_request(req))
        except ProtocolError as exc:
            try:
                with self.send_lock:
                    self.sock.sendall(make_frame(
                        OP_CLOSE, struct.pack(">H", exc.close_code)))
            except OSError:
                pass
        except (ConnectionError, OSError):
            pass
        finally:
            self.alive = False
            self.server.connections.discard(self)
            record_ws_connections(len(self.server.connections))
            try:
                self.sock.close()
            except OSError:
                pass
            # wake the writer so the thread exits; a full queue means
            # the writer is mid-send and will exit on the closed socket
            try:
                self._sendq.put_nowait(None)
            except queue.Full:
                pass


class WsServer:
    """WebSocket endpoint bound to an RpcServer's method table."""

    def __init__(self, rpc_server, host: str = "127.0.0.1", port: int = 0,
                 backlog: int | None = None,
                 notify_queue_size: int = NOTIFY_QUEUE_SIZE,
                 slow_consumer_deadline: float = SLOW_CONSUMER_DEADLINE):
        self.rpc = rpc_server
        self.node = rpc_server.node
        self.notify_queue_size = notify_queue_size
        self.slow_consumer_deadline = slow_consumer_deadline
        # bind eagerly so the port is known before start()
        self.listener = socket.create_server(
            (host, port), backlog=backlog)
        self.host, self.port = self.listener.getsockname()[:2]
        self.connections: set[WsConnection] = set()
        self._stop = threading.Event()
        self.loop: asyncio.AbstractEventLoop | None = None
        self._loop_thread = None
        self._aio_server: asyncio.AbstractServer | None = None
        # push hooks
        self.node.block_listeners.append(self._on_block)
        self.node.mempool.on_add.append(self._on_pending_tx)
        reorg_listeners = getattr(self.node, "reorg_listeners", None)
        if reorg_listeners is not None:
            reorg_listeners.append(self._on_reorg)

    # -- push paths --------------------------------------------------------
    def _on_block(self, block):
        from .serializers import header_to_json

        head_json = None
        logs_cache = None
        for conn in list(self.connections):
            for sub in list(conn.subs.values()):
                if sub.kind == "newHeads":
                    if head_json is None:
                        head_json = header_to_json(block.header, block.hash)
                    conn.notify(sub.sid, head_json)
                elif sub.kind == "logs":
                    if logs_cache is None:
                        logs_cache = self._block_logs(block)
                    for log_json in logs_cache:
                        if _log_matches(log_json, sub.params):
                            conn.notify(sub.sid, log_json)

    def _block_logs(self, block) -> list[dict]:
        receipts = self.node.store.get_receipts(block.hash) or []
        out = []
        log_index = 0
        for tx_index, (tx, receipt) in enumerate(
                zip(block.body.transactions, receipts)):
            for log in receipt.logs:
                out.append({
                    "address": "0x" + log.address.hex(),
                    "topics": ["0x" + bytes(t).hex() for t in log.topics],
                    "data": "0x" + log.data.hex(),
                    "blockNumber": hex(block.header.number),
                    "blockHash": "0x" + block.hash.hex(),
                    "transactionHash": "0x" + tx.hash.hex(),
                    "transactionIndex": hex(tx_index),
                    "logIndex": hex(log_index),
                    "removed": False,
                })
                log_index += 1
        return out

    def _on_reorg(self, outcome):
        """Reorg subscription semantics (docs/CHAIN_RESILIENCE.md):
        first every orphaned block's logs are re-emitted with
        `removed: true` (oldest first, mirroring their original order),
        then the new canonical branch is announced like fresh blocks —
        newHeads for each adopted header plus its logs.  A recovered
        (crash-replayed) reorg has no adopted list; any connected
        subscriber still learns its old logs are gone."""
        for block in outcome.orphaned:
            for log_json in self._block_logs(block):
                removed = dict(log_json)
                removed["removed"] = True
                for conn in list(self.connections):
                    for sub in list(conn.subs.values()):
                        if sub.kind == "logs" \
                                and _log_matches(removed, sub.params):
                            conn.notify(sub.sid, removed)
        for block in outcome.adopted:
            self._on_block(block)

    def _on_pending_tx(self, tx_hash: bytes):
        for conn in list(self.connections):
            for sub in list(conn.subs.values()):
                if sub.kind == "newPendingTransactions":
                    conn.notify(sub.sid, "0x" + tx_hash.hex())

    # -- accept path -------------------------------------------------------
    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter):
        try:
            data = await reader.readuntil(b"\r\n\r\n")
        except (asyncio.IncompleteReadError, asyncio.LimitOverrunError,
                ConnectionError, OSError):
            writer.close()
            return
        key = _parse_handshake(data)
        try:
            if key is None:
                writer.write(b"HTTP/1.1 400 Bad Request\r\n\r\n")
                await writer.drain()
                writer.close()
                return
            writer.write(_handshake_response(key))
            await writer.drain()
        except (ConnectionError, OSError):
            writer.close()
            return
        raw = writer.get_extra_info("socket")
        conn = WsConnection(self, raw, reader=reader, writer=writer)
        self.connections.add(conn)
        record_ws_accept()
        record_ws_connections(len(self.connections))
        await conn.run_async()

    def start(self):
        from .aio import LoopThread

        self._loop_thread = LoopThread(name="ws-loop").start()
        self.loop = self._loop_thread.loop
        self.listener.setblocking(False)

        async def _open():
            return await asyncio.start_server(self._serve,
                                              sock=self.listener)

        self._aio_server = self._loop_thread.call(_open())
        return self

    def stop(self):
        self._stop.set()
        lt = self._loop_thread
        if lt is not None:
            self._loop_thread = None

            async def _close():
                if self._aio_server is not None:
                    self._aio_server.close()
                    await self._aio_server.wait_closed()
                for conn in list(self.connections):
                    conn._abort()

            try:
                lt.call(_close(), timeout=5.0)
            except Exception:  # noqa: BLE001 — hard-stop below reclaims
                pass
            lt.stop()
            self.loop = None
            self._aio_server = None
        try:
            self.listener.close()
        except OSError:
            pass
        for conn in list(self.connections):
            try:
                conn.sock.close()
            except OSError:
                pass


def _log_matches(log_json: dict, params: dict) -> bool:
    addr = params.get("address")
    if addr:
        addrs = [addr] if isinstance(addr, str) else list(addr)
        if log_json["address"].lower() not in \
                (a.lower() for a in addrs):
            return False
    topics = params.get("topics") or []
    have = log_json["topics"]
    for i, want in enumerate(topics):
        if want is None:
            continue
        if i >= len(have):
            return False
        options = [want] if isinstance(want, str) else list(want)
        if have[i].lower() not in (o.lower() for o in options):
            return False
    return True
