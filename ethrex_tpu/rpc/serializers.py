"""JSON serialization of chain objects (RPC wire format)."""

from __future__ import annotations

from ..primitives.block import Block, BlockHeader
from ..primitives.receipt import Receipt
from ..primitives.transaction import Transaction


def hx(v: int) -> str:
    return hex(v)


def hb(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def parse_quantity(v) -> int:
    if isinstance(v, int):
        return v
    return int(v, 16)


def parse_bytes(v: str) -> bytes:
    return bytes.fromhex(v.removeprefix("0x"))


def header_to_json(h: BlockHeader, block_hash: bytes | None = None) -> dict:
    out = {
        "parentHash": hb(h.parent_hash),
        "sha3Uncles": hb(h.uncles_hash),
        "miner": hb(h.coinbase),
        "stateRoot": hb(h.state_root),
        "transactionsRoot": hb(h.tx_root),
        "receiptsRoot": hb(h.receipts_root),
        "logsBloom": hb(h.bloom),
        "difficulty": hx(h.difficulty),
        "number": hx(h.number),
        "gasLimit": hx(h.gas_limit),
        "gasUsed": hx(h.gas_used),
        "timestamp": hx(h.timestamp),
        "extraData": hb(h.extra_data),
        "mixHash": hb(h.prev_randao),
        "nonce": hb(h.nonce),
        "hash": hb(block_hash or h.hash),
    }
    if h.base_fee_per_gas is not None:
        out["baseFeePerGas"] = hx(h.base_fee_per_gas)
    if h.withdrawals_root is not None:
        out["withdrawalsRoot"] = hb(h.withdrawals_root)
    if h.blob_gas_used is not None:
        out["blobGasUsed"] = hx(h.blob_gas_used)
    if h.excess_blob_gas is not None:
        out["excessBlobGas"] = hx(h.excess_blob_gas)
    if h.parent_beacon_block_root is not None:
        out["parentBeaconBlockRoot"] = hb(h.parent_beacon_block_root)
    if h.requests_hash is not None:
        out["requestsHash"] = hb(h.requests_hash)
    return out


def tx_to_json(tx: Transaction, block_hash=None, block_number=None,
               index=None) -> dict:
    out = {
        "type": hx(tx.tx_type),
        "nonce": hx(tx.nonce),
        "gas": hx(tx.gas_limit),
        "value": hx(tx.value),
        "input": hb(tx.data),
        "to": hb(tx.to) if tx.to else None,
        "hash": hb(tx.hash),
        "from": hb(tx.sender() or b"\x00" * 20),
        "v": hx(tx.v), "r": hx(tx.r), "s": hx(tx.s),
    }
    if tx.chain_id is not None:
        out["chainId"] = hx(tx.chain_id)
    if tx.tx_type in (0, 1):
        out["gasPrice"] = hx(tx.gas_price)
    else:
        out["maxFeePerGas"] = hx(tx.max_fee_per_gas)
        out["maxPriorityFeePerGas"] = hx(tx.max_priority_fee_per_gas)
    if tx.tx_type >= 1:
        out["accessList"] = [
            {"address": hb(a), "storageKeys":
             [hb(s.to_bytes(32, "big")) for s in slots]}
            for a, slots in tx.access_list]
    if tx.tx_type == 3:
        out["maxFeePerBlobGas"] = hx(tx.max_fee_per_blob_gas)
        out["blobVersionedHashes"] = [hb(h) for h in tx.blob_versioned_hashes]
    if block_hash is not None:
        out["blockHash"] = hb(block_hash)
        out["blockNumber"] = hx(block_number)
        out["transactionIndex"] = hx(index)
    return out


def block_to_json(block: Block, full_txs: bool = False) -> dict:
    h = block.hash
    out = header_to_json(block.header, h)
    if full_txs:
        out["transactions"] = [
            tx_to_json(tx, h, block.header.number, i)
            for i, tx in enumerate(block.body.transactions)]
    else:
        out["transactions"] = [hb(tx.hash)
                               for tx in block.body.transactions]
    out["uncles"] = []
    if block.body.withdrawals is not None:
        out["withdrawals"] = [{
            "index": hx(w.index), "validatorIndex": hx(w.validator_index),
            "address": hb(w.address), "amount": hx(w.amount),
        } for w in block.body.withdrawals]
    out["size"] = hx(len(block.encode()))
    return out


def receipt_to_json(rec: Receipt, tx: Transaction, block: Block,
                    index: int, gas_price: int, prev_cumulative: int = 0,
                    log_index_base: int = 0) -> dict:
    h = block.hash
    logs = []
    contract = None
    if tx.is_create:
        from ..crypto.keccak import keccak256
        from ..primitives import rlp as _rlp
        contract = hb(keccak256(
            _rlp.encode([tx.sender() or b"\x00" * 20, tx.nonce]))[12:])
    return_obj = {
        "transactionHash": hb(tx.hash),
        "transactionIndex": hx(index),
        "blockHash": hb(h),
        "blockNumber": hx(block.header.number),
        "from": hb(tx.sender() or b"\x00" * 20),
        "to": hb(tx.to) if tx.to else None,
        "cumulativeGasUsed": hx(rec.cumulative_gas_used),
        "gasUsed": hx(rec.cumulative_gas_used - prev_cumulative),
        "contractAddress": contract,
        "logs": logs,
        "logsBloom": hb(rec.bloom),
        "type": hx(rec.tx_type),
        "status": "0x1" if rec.succeeded else "0x0",
        "effectiveGasPrice": hx(gas_price),
    }
    for i, log in enumerate(rec.logs):
        logs.append({
            "address": hb(log.address),
            "topics": [hb(t) for t in log.topics],
            "data": hb(log.data),
            "blockHash": hb(h),
            "blockNumber": hx(block.header.number),
            "transactionHash": hb(tx.hash),
            "transactionIndex": hx(index),
            "logIndex": hx(log_index_base + i),
            "removed": False,
        })
    return return_obj
