"""Chain management: block validation, execution, import (parity with the
reference's crates/blockchain/blockchain.rs — add_block =
validate_block + execute + merkleize + store; pipelined/batch variants come
with the perf rounds).
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading

from ..crypto.keccak import keccak256
from ..primitives import rlp
from ..primitives.account import EMPTY_TRIE_ROOT
from ..primitives.block import Block, BlockHeader
from ..primitives.genesis import ChainConfig, Fork
from ..primitives.receipt import Receipt, logs_bloom
from ..evm import gas as G
from ..evm.db import StateDB
from ..evm.executor import InvalidTransaction, execute_tx
from ..evm.vm import EVM, BlockEnv, Message
from ..storage.store import Store
from ..trie.trie import trie_root_from_items
from . import sender_recovery

ELASTICITY_MULTIPLIER = 2
BASE_FEE_MAX_CHANGE_DENOMINATOR = 8
GAS_LIMIT_ADJUSTMENT_FACTOR = 1024
MIN_GAS_LIMIT = 5000

SYSTEM_ADDRESS = bytes.fromhex("fffffffffffffffffffffffffffffffffffffffe")
BEACON_ROOTS_ADDRESS = bytes.fromhex(
    "000f3df6d732807ef1319fb7b8bb8522d0beac02")
HISTORY_STORAGE_ADDRESS = bytes.fromhex(
    "0000f90827f1c53a10cb7a02335b175320002935")
WITHDRAWAL_REQUESTS_ADDRESS = bytes.fromhex(
    "00000961ef480eb55e80d19ad83579a64c007002")
CONSOLIDATION_REQUESTS_ADDRESS = bytes.fromhex(
    "0000bbddc7ce488642fb579f8b00f3a590007251")
DEPOSIT_CONTRACT_ADDRESS = bytes.fromhex(
    "00000000219ab540356cbb839cbe05303d7705fa")

GWEI = 10**9


class InvalidBlock(Exception):
    pass


def _note_import_stage(stage: str, seconds: float) -> None:
    """Sub-stage attribution (execute / merkleize / store_write) for
    both import paths: the block_import_stage_seconds histogram plus the
    perf profiler tree.  Telemetry contract: never raises into an
    import."""
    try:
        from ..perf.profiler import record_stage
        from ..utils.metrics import observe_import_stage

        observe_import_stage(stage, seconds)
        record_stage("l1_import", stage, seconds)
    except Exception:
        pass


class DirtySnapshot:
    """Frozen copy of one block's dirty write set, duck-typing the slice
    of StateDB that apply_updates_to_tries consumes (dirty_accounts,
    dirty_storage, accounts, get_storage, source).  Lets the pipelined
    importer merkleize block N on a worker thread while block N+1 keeps
    executing — and mutating — the live StateDB."""

    def __init__(self, db: StateDB):
        self.dirty_accounts = set(db.dirty_accounts)
        self.dirty_storage = {a: set(s)
                              for a, s in db.dirty_storage.items()}
        self.accounts = {}
        for addr in self.dirty_accounts | set(self.dirty_storage):
            acct = db.accounts.get(addr)
            if acct is None:
                continue
            frozen = dataclasses.replace(acct)
            frozen.storage = dict(acct.storage)
            self.accounts[addr] = frozen
        self.source = None  # the worker chains StoreSource(prev_root)

    def get_storage(self, addr: bytes, slot: int) -> int:
        acct = self.accounts[addr]
        if slot in acct.storage:
            return acct.storage[slot]
        if acct.exists and not acct.storage_cleared:
            return self.source.get_storage(addr, slot)
        return 0


@dataclasses.dataclass
class ExecutionOutcome:
    receipts: list
    state_db: StateDB
    gas_used: int
    blob_gas_used: int
    requests: list  # raw request bytes (type || data), non-empty only


class Blockchain:
    def __init__(self, store: Store, config: ChainConfig):
        self.store = store
        self.config = config

    # ------------------------------------------------------------------
    # header validation (parent-relative)
    # ------------------------------------------------------------------
    def validate_header(self, header: BlockHeader, parent: BlockHeader):
        if header.number != parent.number + 1:
            raise InvalidBlock("bad block number")
        if header.timestamp <= parent.timestamp:
            raise InvalidBlock("timestamp not after parent")
        if len(header.extra_data) > 32:
            raise InvalidBlock("extra data too long")
        fork = self.config.fork_at(header.number, header.timestamp)
        # gas limit bounds
        diff = abs(header.gas_limit - parent.gas_limit)
        if diff >= parent.gas_limit // GAS_LIMIT_ADJUSTMENT_FACTOR:
            raise InvalidBlock("gas limit change too large")
        if header.gas_limit < MIN_GAS_LIMIT:
            raise InvalidBlock("gas limit too low")
        if header.gas_used > header.gas_limit:
            raise InvalidBlock("gas used above limit")
        if fork >= Fork.LONDON:
            expected = next_base_fee(parent)
            if header.base_fee_per_gas != expected:
                raise InvalidBlock(
                    f"bad base fee {header.base_fee_per_gas} != {expected}")
        if fork >= Fork.PARIS:
            if header.difficulty != 0 or header.nonce != b"\x00" * 8:
                raise InvalidBlock("post-merge difficulty/nonce must be zero")
        if fork >= Fork.SHANGHAI and header.withdrawals_root is None:
            raise InvalidBlock("missing withdrawals root")
        if fork >= Fork.CANCUN:
            if header.blob_gas_used is None or header.excess_blob_gas is None:
                raise InvalidBlock("missing blob gas fields")
            # spec + reference (block.rs validate_excess_blob_gas): the
            # schedule and fork are resolved at the NEW block's timestamp
            target, max_bg, fraction = self.config.blob_params_at(
                header.timestamp)
            expected_excess = G.calc_excess_blob_gas(
                parent.excess_blob_gas or 0, parent.blob_gas_used or 0,
                target, max_bg, fraction,
                parent_base_fee=parent.base_fee_per_gas or 0,
                eip7918=fork >= Fork.OSAKA)
            if header.excess_blob_gas != expected_excess:
                raise InvalidBlock("bad excess blob gas")
            if header.parent_beacon_block_root is None:
                raise InvalidBlock("missing parent beacon block root")
        if fork >= Fork.PRAGUE and header.requests_hash is None:
            raise InvalidBlock("missing requests hash")

    # ------------------------------------------------------------------
    # system operations
    # ------------------------------------------------------------------
    def _system_call(self, state: StateDB, block_env: BlockEnv,
                     target: bytes, data: bytes):
        if not state.get_code(target):
            return None
        evm = EVM(state, block_env, self.config)
        ok, _, out = evm.execute_message(Message(
            caller=SYSTEM_ADDRESS, to=target, code_address=target,
            value=0, data=data, gas=30_000_000))
        return out if ok else None

    def _pre_tx_system_ops(self, state: StateDB, env: BlockEnv,
                           header: BlockHeader, fork: Fork):
        state.begin_tx()
        if fork >= Fork.CANCUN and header.parent_beacon_block_root:
            self._system_call(state, env, BEACON_ROOTS_ADDRESS,
                              header.parent_beacon_block_root)
        if fork >= Fork.PRAGUE:
            self._system_call(state, env, HISTORY_STORAGE_ADDRESS,
                              header.parent_hash)
        state.finalize_tx()

    def _post_tx_requests(self, state: StateDB, env: BlockEnv,
                          receipts: list, fork: Fork) -> list:
        if fork < Fork.PRAGUE:
            return []
        requests = []
        # EIP-6110 deposits from the deposit contract logs
        deposit_data = b""
        for rec in receipts:
            for log in rec.logs:
                if log.address == DEPOSIT_CONTRACT_ADDRESS and log.topics:
                    deposit_data += _parse_deposit_log(log.data)
        if deposit_data:
            requests.append(b"\x00" + deposit_data)
        state.begin_tx()
        out = self._system_call(state, env, WITHDRAWAL_REQUESTS_ADDRESS, b"")
        if out:
            requests.append(b"\x01" + out)
        out = self._system_call(state, env, CONSOLIDATION_REQUESTS_ADDRESS,
                                b"")
        if out:
            requests.append(b"\x02" + out)
        state.finalize_tx()
        return requests

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def execute_block(self, block: Block, parent: BlockHeader,
                      state_db: StateDB | None = None,
                      bal_recorder=None) -> ExecutionOutcome:
        """`bal_recorder` (primitives/bal.BalRecorder, optional) collects
        the EIP-7928 Block Access List from the per-phase journals —
        index 0 = pre-exec system ops, 1..n = txs, n+1 = post
        (withdrawals + requests), mirroring the reference recorder
        (block_access_list.rs:791-795)."""
        header = block.header
        fork = self.config.fork_at(header.number, header.timestamp)
        env = BlockEnv(
            number=header.number, coinbase=header.coinbase,
            timestamp=header.timestamp, gas_limit=header.gas_limit,
            prev_randao=header.prev_randao,
            base_fee=header.base_fee_per_gas or 0,
            excess_blob_gas=header.excess_blob_gas or 0,
            parent_beacon_block_root=header.parent_beacon_block_root
            or b"\x00" * 32,
            difficulty=header.difficulty,
        )
        state = state_db or self.store.state_db(parent.state_root)
        if bal_recorder is not None:
            bal_recorder.attach(state)
        self._pre_tx_system_ops(state, env, header, fork)
        if bal_recorder is not None:
            bal_recorder.record_phase(state, 0)

        receipts = []
        gas_used = 0
        blob_gas_used = 0
        for i, tx in enumerate(block.body.transactions):
            try:
                result = execute_tx(tx, state, env, self.config)
            except InvalidTransaction as e:
                raise InvalidBlock(f"tx {i} invalid: {e}")
            if bal_recorder is not None:
                bal_recorder.record_phase(state, i + 1)
            gas_used += result.gas_used
            if gas_used > header.gas_limit:
                raise InvalidBlock("block gas limit exceeded")
            blob_gas_used += G.BLOB_GAS_PER_BLOB * len(
                tx.blob_versioned_hashes)
            receipts.append(Receipt(
                tx_type=tx.tx_type, succeeded=result.success,
                cumulative_gas_used=gas_used, logs=result.logs))
        _, max_blob_gas, _ = self.config.blob_params_at(header.timestamp)
        if blob_gas_used > max_blob_gas:
            raise InvalidBlock("blob gas above maximum")

        post_index = len(block.body.transactions) + 1
        # withdrawals
        had_post_ops = False
        if block.body.withdrawals:
            for wd in block.body.withdrawals:
                if wd.amount:
                    state.begin_tx()
                    state.add_balance(wd.address, wd.amount * GWEI)
                    state.finalize_tx()
                    had_post_ops = True
        requests = self._post_tx_requests(state, env, receipts, fork)
        # ONE record for the whole post-exec phase (withdrawals +
        # requests): per-withdrawal records would emit duplicate
        # block_access_index entries for a shared withdrawal address and
        # the honest BAL would fail its own ordering check (review
        # finding); the journal sink accumulates across the windows
        if bal_recorder is not None and \
                (had_post_ops or fork >= Fork.PRAGUE):
            bal_recorder.record_phase(state, post_index)
        return ExecutionOutcome(receipts=receipts, state_db=state,
                                gas_used=gas_used,
                                blob_gas_used=blob_gas_used,
                                requests=requests)

    # ------------------------------------------------------------------
    # import
    # ------------------------------------------------------------------
    def _validate_block_outcome(self, header: BlockHeader,
                                outcome: "ExecutionOutcome") -> None:
        """Post-execution consensus checks shared by the per-block and
        batch import paths (gas, blob gas, receipts root, bloom, Prague
        requests) — everything except the state root."""
        if outcome.gas_used != header.gas_used:
            raise InvalidBlock(
                f"gas used mismatch in block {header.number}: "
                f"{outcome.gas_used} != {header.gas_used}")
        if header.blob_gas_used is not None \
                and outcome.blob_gas_used != header.blob_gas_used:
            raise InvalidBlock(
                f"blob gas used mismatch in block {header.number}")
        if compute_receipts_root(outcome.receipts) != header.receipts_root:
            raise InvalidBlock(
                f"receipts root mismatch in block {header.number}")
        bloom = logs_bloom(
            [log for r in outcome.receipts for log in r.logs])
        if bloom != header.bloom:
            raise InvalidBlock(f"logs bloom mismatch in block {header.number}")
        fork = self.config.fork_at(header.number, header.timestamp)
        if fork >= Fork.PRAGUE:
            if compute_requests_hash(outcome.requests) != \
                    header.requests_hash:
                raise InvalidBlock(
                    f"requests hash mismatch in block {header.number}")

    def regenerate_head_state(self) -> int:
        """Re-execute the canonical tail whose trie nodes never reached
        the durable backend (diff layering keeps unfinalized state in
        RAM; a restart must rebuild it — the reference makes the same
        trade, ethrex.rs:62-64 / initializers regenerate_head_state).

        Walks back from the head to the newest ancestor whose state root
        resolves, then re-imports forward.  Layers flatten oldest-first
        and atomically per block, so root presence implies completeness.
        Returns the number of re-imported blocks."""
        head = self.store.head_header()
        if head is None or self.store.nodes.get(head.state_root) is not None:
            return 0
        tail = []
        cursor = head
        while cursor.number > 0 and \
                self.store.nodes.get(cursor.state_root) is None:
            body = self.store.get_body(cursor.hash)
            if body is None:
                break
            tail.append(Block(header=cursor, body=body))
            cursor = self.store.get_header(cursor.parent_hash)
            if cursor is None:
                break
        for block in reversed(tail):
            self.add_block(block)
        return len(tail)

    def add_block(self, block: Block, bal=None) -> None:
        """`bal` (primitives/bal.BlockAccessList, optional): the claimed
        EIP-7928 Block Access List.  When given, the import prefetches
        the listed state in parallel (warm_from_bal), re-derives the BAL
        during execution, and REJECTS the block if the claim does not
        match — a tampered list cannot ride a valid block (reference:
        blockchain.rs:552 BAL validation)."""
        import time as _time

        from ..utils.metrics import (observe_block_execution,
                                     observe_block_import)

        t_import = _time.perf_counter()
        header = block.header
        parent = self.store.get_header(header.parent_hash)
        if parent is None:
            raise InvalidBlock("unknown parent")
        self.validate_header(header, parent)
        self._validate_body_roots(block)
        # batched sender recovery ahead of execution (ethrex
        # add_block_pipeline): the executor's inline tx.sender() becomes
        # a cache hit; the batch wall lands in evm/sig_recovery
        sender_recovery.recover_senders(block.body.transactions)
        # diff layering (storage/layering.py): this block's trie nodes go
        # into a per-block in-memory layer; settling flattens layers to
        # the durable backend once finalized (or past the settle window)
        self.store.push_node_layer(header.number, header.hash)
        try:
            recorder = None
            state_db = None
            if bal is not None:
                from ..primitives.bal import BalRecorder

                try:
                    bal.validate_ordering()
                except ValueError as e:
                    raise InvalidBlock(f"block access list: {e}")
                recorder = BalRecorder()
                state_db = self.store.state_db(parent.state_root)
                self.warm_from_bal(state_db, bal)
            t_exec = _time.perf_counter()
            outcome = self.execute_block(block, parent, state_db,
                                         bal_recorder=recorder)
            dt_exec = _time.perf_counter() - t_exec
            observe_block_execution(dt_exec)
            _note_import_stage("execute", dt_exec)
            self._validate_block_outcome(header, outcome)
            if recorder is not None and \
                    recorder.build().hash() != bal.hash():
                raise InvalidBlock("block access list mismatch")
            t_mk = _time.perf_counter()
            new_root = self.store.apply_account_updates(
                parent.state_root, outcome.state_db)
            _note_import_stage("merkleize", _time.perf_counter() - t_mk)
            if new_root != header.state_root:
                raise InvalidBlock(
                    f"state root mismatch: {new_root.hex()} != "
                    f"{header.state_root.hex()}")
        except BaseException:
            # a failed import must not leak an orphaned top layer that
            # would absorb unrelated writes (review finding)
            self.store.discard_node_layer(header.number, header.hash)
            raise
        t_sw = _time.perf_counter()
        self.store.add_block(block, outcome.receipts)
        _note_import_stage("store_write", _time.perf_counter() - t_sw)
        observe_block_import(_time.perf_counter() - t_import)

    def generate_bal(self, block: Block, parent: BlockHeader):
        """Derive the block's EIP-7928 Block Access List (builder side:
        the reference generates it during payload building,
        blockchain.rs:552)."""
        from ..primitives.bal import BalRecorder

        recorder = BalRecorder()
        self.execute_block(block, parent, bal_recorder=recorder)
        return recorder.build()

    def warm_from_bal(self, state_db: StateDB, bal) -> None:
        """BAL-driven state prefetch (the reference's warm_block_from_bal
        seat, levm/mod.rs:2817): pull every listed account, its code and
        its listed slots into the execution cache before the first tx
        runs.  On a multi-core host the per-account fetches fan out over
        a thread pool — the trie-walk keccaks and the native extensions
        drop the GIL; single-core hosts prefetch inline (same cache
        effect, no fan-out)."""
        import os

        accounts = bal.accounts
        if not accounts:
            return
        # warm the SOURCE layer only (trie objects + node caches), never
        # the StateDB account cache: a pre-seeded StateDB slot skips the
        # read journal during execution, so the derived BAL would lose
        # honest reads — and journaled warming loads would let a claimed
        # list padded with bogus reads self-certify (review findings)
        src = state_db.source

        def prefetch(ac):
            try:
                src.get_account_state(ac.address)
                for slot in ac.storage_reads:
                    src.get_storage(ac.address, slot)
                for slot in ac.storage_changes:
                    src.get_storage(ac.address, slot)
            except Exception:
                pass  # missing state surfaces during execution

        cpus = os.cpu_count() or 1
        if cpus > 1 and len(accounts) > 8:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=min(8, cpus)) as pool:
                list(pool.map(prefetch, accounts))
        else:
            for ac in accounts:
                prefetch(ac)

    def add_blocks_pipelined(self, blocks: list[Block]) -> None:
        """Pipelined import: execute block N+1 WHILE block N merkleizes
        and stores on a worker thread (reference: blockchain.rs
        add_block_pipeline + execute_block_pipeline streaming account
        updates to the merkleizer).  Unlike the batch path, EVERY block's
        state root is validated.  The overlap is real under CPython: the
        merkleize step runs in the native C++ MPT engine via ctypes,
        which releases the GIL.

        Execution state chains through one shared StateDB cache; each
        block's dirty writes are snapshotted (DirtySnapshot) at handoff,
        and the worker chains the trie roots block by block."""
        import time as _time

        if not blocks:
            return
        # one diff layer per BATCH, tagged by its tail block: bulk-imported
        # nodes settle when the tail settles instead of being attributed
        # to whatever unrelated layer was open (review finding)
        self.store.push_node_layer(blocks[-1].header.number,
                                   blocks[-1].header.hash)
        t0 = _time.perf_counter()
        try:
            self._add_blocks_pipelined(blocks)
        except BaseException:
            # mirror add_block: a failed pipelined import must not leak
            # the batch layer (it would absorb unrelated writes and stall
            # their durability behind a never-imported tail block)
            self.store.discard_node_layer(blocks[-1].header.number,
                                          blocks[-1].header.hash)
            raise
        wall = _time.perf_counter() - t0
        try:
            from ..utils.metrics import record_import_throughput

            gas = sum(b.header.gas_used for b in blocks)
            if wall > 0:
                record_import_throughput(gas / wall / 1e6)
        except Exception:
            pass

    def _add_blocks_pipelined(self, blocks: list[Block]) -> None:
        import queue as queue_mod
        import time as _time

        from ..evm.db import StateDB
        from ..storage.store import StoreSource

        parent = self.store.get_header(blocks[0].header.parent_hash)
        if parent is None:
            raise InvalidBlock("unknown parent")
        overrides = {parent.number: parent.hash}
        state_db = StateDB(StoreSource(self.store, parent.state_root,
                                       header_overrides=overrides))
        q: queue_mod.Queue = queue_mod.Queue(maxsize=2)
        failure: list[Exception] = []

        def merkleizer():
            prev_root = parent.state_root
            while True:
                item = q.get()
                if item is None:
                    return
                block, receipts, snap = item
                try:
                    snap.source = StoreSource(self.store, prev_root,
                                              header_overrides=overrides)
                    t_mk = _time.perf_counter()
                    new_root = self.store.apply_account_updates(
                        prev_root, snap)
                    _note_import_stage(
                        "merkleize", _time.perf_counter() - t_mk)
                    if new_root != block.header.state_root:
                        raise InvalidBlock(
                            f"state root mismatch at block "
                            f"{block.header.number}: {new_root.hex()} != "
                            f"{block.header.state_root.hex()}")
                    t_sw = _time.perf_counter()
                    self.store.add_block(block, receipts)
                    _note_import_stage(
                        "store_write", _time.perf_counter() - t_sw)
                    prev_root = new_root
                except Exception as exc:  # noqa: BLE001 — joined below
                    failure.append(exc)
                    # keep draining so the producer's put() never blocks
                    # against a dead consumer
                    while q.get() is not None:
                        pass
                    return

        worker = threading.Thread(target=merkleizer, daemon=True)
        worker.start()
        prev = parent
        # overlap sender recovery with execution: block N+1's senders
        # recover on the pool while block N executes/merkleizes (the
        # native engine's C calls drop the GIL, so this is real overlap)
        pending = sender_recovery.recover_senders_async(
            blocks[0].body.transactions)
        try:
            for i, block in enumerate(blocks):
                if failure:
                    break
                header = block.header
                if header.parent_hash != prev.hash:
                    raise InvalidBlock("non-contiguous batch")
                self.validate_header(header, prev)
                self._validate_body_roots(block)
                nxt = None
                if i + 1 < len(blocks):
                    nxt = sender_recovery.recover_senders_async(
                        blocks[i + 1].body.transactions)
                pending.wait()
                t_exec = _time.perf_counter()
                outcome = self.execute_block(block, prev, state_db)
                _note_import_stage("execute", _time.perf_counter() - t_exec)
                if nxt is not None:
                    pending = nxt
                self._validate_block_outcome(header, outcome)
                snap = DirtySnapshot(state_db)
                state_db.drain_dirty()
                q.put((block, outcome.receipts, snap))
                overrides[header.number] = header.hash
                prev = header
        finally:
            q.put(None)
            worker.join()
        if failure:
            raise failure[0]

    VERIFY_INTERVAL = 256  # bound on unverified intermediate state roots

    def add_blocks_in_batch(self, blocks: list[Block]) -> None:
        """Bulk import: execute every block against ONE shared state cache
        and merkleize at VERIFY_INTERVAL boundaries + the end (reference:
        blockchain.rs add_blocks_in_batch — full-sync bulk path).  All
        header/body rules, receipts roots, blooms and gas are validated per
        block; state roots are validated every VERIFY_INTERVAL blocks and
        for the final block, so a malicious bulk peer can persist at most
        VERIFY_INTERVAL-1 headers with bogus intermediate roots before the
        whole batch is rejected (bounding the trusted-chunk trade the
        reference makes for bulk sync throughput)."""
        from ..storage.store import StoreSource

        if not blocks:
            return
        parent = self.store.get_header(blocks[0].header.parent_hash)
        if parent is None:
            raise InvalidBlock("unknown parent")
        # recover every sender in the batch up front, in one parallel
        # pass (ethrex add_blocks_in_batch recovers ahead of the loop)
        sender_recovery.recover_senders(
            [tx for b in blocks for tx in b.body.transactions])
        overrides = {parent.number: parent.hash}
        source = StoreSource(self.store, parent.state_root,
                             header_overrides=overrides)
        state_db = StateDB(source)
        prev = parent
        per_block = []
        verified_root = parent.state_root
        for i, block in enumerate(blocks):
            header = block.header
            if header.parent_hash != prev.hash:
                raise InvalidBlock("non-contiguous batch")
            self.validate_header(header, prev)
            self._validate_body_roots(block)
            outcome = self.execute_block(block, prev, state_db)
            self._validate_block_outcome(header, outcome)
            per_block.append((block, outcome.receipts))
            overrides[header.number] = header.hash
            prev = header
            if (i + 1) % self.VERIFY_INTERVAL == 0 and i + 1 < len(blocks):
                verified_root = self.store.apply_account_updates(
                    verified_root, state_db)
                if verified_root != header.state_root:
                    raise InvalidBlock(
                        f"intermediate state root mismatch at block "
                        f"{header.number}: {verified_root.hex()} != "
                        f"{header.state_root.hex()}")
                state_db.rebase(StoreSource(self.store, verified_root,
                                            header_overrides=overrides))
        new_root = self.store.apply_account_updates(verified_root, state_db)
        if new_root != blocks[-1].header.state_root:
            raise InvalidBlock(
                f"final state root mismatch: {new_root.hex()} != "
                f"{blocks[-1].header.state_root.hex()}")
        for block, receipts in per_block:
            self.store.add_block(block, receipts)

    def _validate_body_roots(self, block: Block):
        header = block.header
        if compute_tx_root(block.body.transactions) != header.tx_root:
            raise InvalidBlock("transactions root mismatch")
        if block.body.withdrawals is not None:
            wroot = compute_withdrawals_root(block.body.withdrawals)
            if wroot != header.withdrawals_root:
                raise InvalidBlock("withdrawals root mismatch")
        if header.uncles_hash != keccak256(rlp.encode(block.body.uncles)):
            raise InvalidBlock("uncles hash mismatch")


def _parse_deposit_log(data: bytes) -> bytes:
    """Extract the 7685 deposit request payload from a deposit-event log."""
    # DepositEvent(bytes pubkey, bytes wc, bytes amount, bytes sig, bytes idx)
    # ABI-encoded dynamic fields; offsets at fixed positions.
    try:
        out = b""
        for i in range(5):
            off = int.from_bytes(data[32 * i:32 * (i + 1)], "big")
            ln = int.from_bytes(data[off:off + 32], "big")
            out += data[off + 32:off + 32 + ln]
        return out
    except Exception:
        return b""


def next_base_fee(parent: BlockHeader) -> int:
    """EIP-1559 base fee update."""
    if parent.base_fee_per_gas is None:
        return 1_000_000_000  # first London block
    parent_base = parent.base_fee_per_gas
    target = parent.gas_limit // ELASTICITY_MULTIPLIER
    if parent.gas_used == target:
        return parent_base
    if parent.gas_used > target:
        delta = max(
            parent_base * (parent.gas_used - target) // target
            // BASE_FEE_MAX_CHANGE_DENOMINATOR, 1)
        return parent_base + delta
    delta = parent_base * (target - parent.gas_used) // target \
        // BASE_FEE_MAX_CHANGE_DENOMINATOR
    return parent_base - delta


def compute_tx_root(txs) -> bytes:
    return trie_root_from_items(
        [(rlp.encode(i), tx.encode_canonical()) for i, tx in enumerate(txs)])


def compute_receipts_root(receipts) -> bytes:
    return trie_root_from_items(
        [(rlp.encode(i), r.encode()) for i, r in enumerate(receipts)])


def compute_withdrawals_root(withdrawals) -> bytes:
    return trie_root_from_items(
        [(rlp.encode(i), rlp.encode(w.to_fields()))
         for i, w in enumerate(withdrawals)])


def compute_requests_hash(requests: list[bytes]) -> bytes:
    acc = hashlib.sha256()
    for req in requests:
        if len(req) > 1:
            acc.update(hashlib.sha256(req).digest())
    return acc.digest()
