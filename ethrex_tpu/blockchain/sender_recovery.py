"""Batched, parallel transaction sender recovery — off the execute path.

ethrex recovers a block's senders ahead of execution instead of inline in
the tx loop (`add_block_pipeline` / `add_blocks_in_batch`); this module is
that stage.  `recover_senders(txs)` recovers every uncached sender in one
batched pass and seeds each tx's `_sender` cache (including the
failed-recovery sentinel), so the executor's inline `tx.sender()` becomes
a dict-speed cache hit.

Engine selection:

* **native present** (`crypto/native_secp256k1.py`, built from
  `native/secp256k1.c`): the tx list is sliced across a bounded thread
  pool and each worker runs one C `recover_batch` call over its slice.
  The C call releases the GIL, so the slices recover genuinely in
  parallel.
* **native absent**: serial pure-Python recovery — threads cannot help a
  GIL-bound big-int loop, and correctness must not depend on the native
  build.

Pool sizing: `ETHREX_SENDER_WORKERS` env or `configure(workers=...)`
(wired to `--sender-workers`); default `min(8, cpu_count)`.

The batched wall-clock is recorded into the existing `evm/sig_recovery`
profiler stage so PR-6's attribution stays honest — after this stage runs,
the executor's own per-tx `sig_recovery` samples are cache hits (~µs), and
the batch wall carries the real cost.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

from ..crypto import native_secp256k1, secp256k1
from ..perf.profiler import record_stage
from ..primitives.transaction import SENDER_INVALID, TYPE_PRIVILEGED
from ..utils import metrics

_HALF_N = secp256k1.N // 2

_lock = threading.Lock()
_configured: int | None = None
_pool: ThreadPoolExecutor | None = None
_pool_size = 0


def configure(workers: int | None) -> None:
    """Set the worker-pool size (CLI `--sender-workers`).  `None` keeps
    the env/default resolution; the pool is rebuilt lazily on change."""
    global _configured
    with _lock:
        _configured = int(workers) if workers else None


def worker_count() -> int:
    """Resolved pool size: configure() > ETHREX_SENDER_WORKERS > default."""
    if _configured:
        return max(1, _configured)
    env = os.environ.get("ETHREX_SENDER_WORKERS", "")
    try:
        if env and int(env) > 0:
            return int(env)
    except ValueError:
        pass
    return max(1, min(8, os.cpu_count() or 1))


def _get_pool() -> ThreadPoolExecutor:
    global _pool, _pool_size
    size = worker_count()
    with _lock:
        if _pool is None or _pool_size != size:
            if _pool is not None:
                _pool.shutdown(wait=False)
            _pool = ThreadPoolExecutor(
                max_workers=size, thread_name_prefix="sender-recovery")
            _pool_size = size
        return _pool


def _collect(txs):
    """Uncached signature work items: (tx, msg_hash, r, s, rec_id).

    Invalid-by-inspection txs (high-s, bad v) get their sentinel seeded
    here — no EC math needed for those.
    """
    items = []
    for tx in txs:
        if tx.tx_type == TYPE_PRIVILEGED or tx._sender is not None:
            continue
        if tx.s > _HALF_N:
            tx._sender = SENDER_INVALID
            continue
        rec = tx.recovery_id()
        if rec is None:
            tx._sender = SENDER_INVALID
            continue
        items.append((tx, tx.signing_hash(), tx.r, tx.s, rec))
    return items


def _recover_slice_native(items):
    from ..crypto.keccak import keccak256

    pubs = native_secp256k1.recover_batch(
        [(msg, r, s, rec) for _, msg, r, s, rec in items])
    for (tx, _, _, _, _), pub in zip(items, pubs):
        tx._sender = SENDER_INVALID if pub is None else keccak256(pub)[12:]


def _recover_serial_python(items):
    for tx, msg, r, s, rec in items:
        addr = secp256k1.recover_address(msg, r, s, rec)
        tx._sender = SENDER_INVALID if addr is None else addr


def recover_senders(txs, record: bool = True) -> int:
    """Recover and cache the sender of every tx in `txs`.

    Returns the number of signatures actually recovered (cache hits and
    invalid-by-inspection txs are excluded).  Safe to call concurrently
    with readers of `tx.sender()` for *other* txs; callers overlap it
    with the previous block's execute/merkleize, never with execution of
    the same txs.
    """
    items = _collect(txs)
    if not items:
        return 0
    t0 = time.perf_counter()
    if native_secp256k1.available():
        pool = _get_pool()
        size = _pool_size
        # one batched C call per worker slice; slices of < 4 sigs are not
        # worth a dispatch, so small blocks collapse to fewer slices
        per = max(4, (len(items) + size - 1) // size)
        slices = [items[i:i + per] for i in range(0, len(items), per)]
        if len(slices) == 1:
            _recover_slice_native(slices[0])
        else:
            list(pool.map(_recover_slice_native, slices))
    else:
        _recover_serial_python(items)
    wall = time.perf_counter() - t0
    if record:
        record_stage("evm", "sig_recovery", wall)
        metrics.record_senders_recovered(len(items))
        metrics.observe_sender_recovery_batch(wall)
    return len(items)


class PendingRecovery:
    """Handle to an in-flight background recovery (pipeline overlap)."""

    def __init__(self, thread: threading.Thread | None):
        self._thread = thread

    def wait(self, timeout: float | None = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)


_DONE = PendingRecovery(None)  # empty batch: wait() is a no-op


def recover_senders_async(txs) -> PendingRecovery:
    """Kick off recovery for `txs` on a background thread and return a
    handle; used by the pipelined importer to overlap block N+1's sender
    recovery with block N's execute/merkleize.  Exceptions are swallowed
    — the executor's inline recovery is the correctness backstop."""
    if not txs:
        return _DONE

    def run():
        try:
            recover_senders(txs)
        except Exception:
            pass

    t = threading.Thread(target=run, daemon=True,
                         name="sender-recovery-prefetch")
    t.start()
    return PendingRecovery(t)
