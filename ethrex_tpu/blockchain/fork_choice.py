"""Fork choice application (parity with the reference's
crates/blockchain/fork_choice.rs apply_fork_choice)."""

from __future__ import annotations

from ..storage.store import Store


class ForkChoiceError(Exception):
    pass


def apply_fork_choice(store: Store, head_hash: bytes,
                      safe_hash: bytes = b"", finalized_hash: bytes = b""):
    """Make head_hash canonical: walk back to the first ancestor already on
    the canonical chain, rewrite the canonical index, update head/safe/
    finalized markers.  Returns the new head header."""
    head = store.get_header(head_hash)
    if head is None:
        raise ForkChoiceError("unknown head block")
    fin = None
    for name, h in (("safe", safe_hash), ("finalized", finalized_hash)):
        if h:
            hdr = store.get_header(h)
            if hdr is None:
                raise ForkChoiceError(f"unknown {name} block")
            if name == "finalized":
                fin = hdr

    # collect the branch from head back to a canonical ancestor
    branch = []
    cursor = head
    while store.canonical_hash(cursor.number) != cursor.hash:
        branch.append(cursor)
        parent = store.get_header(cursor.parent_hash)
        if parent is None:
            raise ForkChoiceError("detached branch")
        cursor = parent
    # the canonical rewrite + head/safe/finalized markers commit as one
    # journaled unit on persistent stores: a crash mid-fork-choice must
    # not leave the canonical index pointing at a mix of old and new
    # branches
    with store.write_group():
        # drop any stale canonical entries above the new head
        old_head = store.head_header()
        for number in range(head.number + 1, old_head.number + 1):
            store.canonical.pop(number, None)
        for header in branch:
            store.set_canonical(header.number, header.hash)
        store.set_head(head_hash)
        if safe_hash:
            store.meta["safe"] = safe_hash
        if finalized_hash:
            store.meta["finalized"] = finalized_hash
            # flatten every layer at or below the finalized height to the
            # durable backend (see Store.finalize_node_layers)
            store.finalize_node_layers(fin.number)
    return head
