"""Fork choice application (parity with the reference's
crates/blockchain/fork_choice.rs apply_fork_choice), plus the reorg-safe
transaction lifecycle around it (docs/CHAIN_RESILIENCE.md).

`ReorgHandler.apply` is the one seam every head move goes through: it
computes the (orphaned, adopted) block sets from the branch walk,
rewrites the canonical index AND the tx-location index in one journaled
write group, then settles the mempool — orphaned-but-not-readopted txs
are re-injected through the typed `reinjected` path, newly-adopted txs
are evicted, and the surviving pool is revalidated against the new
canonical state.  The invariant enforced end to end: no transaction is
ever silently lost by a reorg.

The mempool leg runs AFTER the canonical write group commits, so a
crash between the two would lose the re-injection — the write group
therefore also journals the orphan set under `meta["reorg_pending"]`,
and `recover_pending` (run on node start and at the top of every apply)
replays the mempool leg until a later write group clears the record.
Crash-only design: the reorg transition is a journaled, restartable
unit like every other state change.
"""

from __future__ import annotations

import threading

from ..primitives.transaction import TYPE_BLOB
from ..storage.store import Store
from ..utils.faults import inject
from ..utils.metrics import (record_chain_reorg,
                             record_mempool_reorg_eviction)

REORG_JOURNAL_KEY = "reorg_pending"


class ForkChoiceError(Exception):
    pass


class InvalidForkChoiceState(ForkChoiceError):
    """safe/finalized hash is known but NOT an ancestor of the new head
    (the engine API's invalidForkChoiceState condition, error -38002)."""


class ReorgOutcome:
    """What one fork-choice application did.  `depth` counts orphaned
    formerly-canonical blocks — 0 for a plain head extension."""

    __slots__ = ("head", "adopted", "orphaned", "depth", "reinjected",
                 "evicted", "pruned", "recovered")

    def __init__(self, head, adopted, orphaned, recovered=False):
        self.head = head            # new head BlockHeader
        self.adopted = adopted      # new canonical Blocks, oldest first
        self.orphaned = orphaned    # ex-canonical Blocks, oldest first
        self.depth = len(orphaned)
        self.reinjected = 0         # txs put back in the pool
        self.evicted = 0            # pool txs dropped (adopted + prunes)
        self.pruned: dict[str, int] = {}  # revalidation prunes by reason
        self.recovered = recovered  # replayed from the pending journal


def _is_ancestor(store: Store, hdr, head) -> bool:
    """True if hdr is head or an ancestor of head (walked by parent
    hashes — the canonical index may not reflect head's branch yet)."""
    if hdr.number > head.number:
        return False
    cursor = head
    while cursor.number > hdr.number:
        cursor = store.get_header(cursor.parent_hash)
        if cursor is None:
            return False
    return cursor.hash == hdr.hash


class ReorgHandler:
    """The reorg seam: owns fork-choice application for one store and
    (when wired by the node) the mempool settlement + subscriber
    notifications that must follow every reorg.  Store-only callers
    (CLI, benches, the L2 sequencer tip mover) construct one ad hoc via
    `apply_fork_choice` and get the canonical/txloc rewrite without the
    pool leg."""

    def __init__(self, store: Store, mempool=None, lock=None):
        self.store = store
        self.mempool = mempool
        # serialization with the node's producer/import paths; a bare
        # handler gets a private lock
        self.lock = lock if lock is not None else threading.RLock()
        # reorg observers (the websocket server re-emits newHeads for
        # the adopted branch and removed:true for orphaned logs)
        self.listeners: list = []
        # handler-local tallies so ethrex_health survives metric
        # registry resets (same idiom as Mempool flow accounting)
        self.reorgs = 0
        self.last_depth = 0
        self.deepest = 0
        self.reinjected = 0
        self.evictions: dict[str, int] = {}
        self.recoveries = 0

    # -- the seam ----------------------------------------------------------
    def apply(self, head_hash: bytes, safe_hash: bytes = b"",
              finalized_hash: bytes = b"") -> ReorgOutcome:
        """Make head_hash canonical: walk back to the first ancestor
        already on the canonical chain, rewrite the canonical + txloc
        indices as one journaled unit, then settle the mempool and
        notify subscribers.  Raises ForkChoiceError for unknown or
        non-ancestor safe/finalized hashes."""
        store = self.store
        with self.lock:
            head = store.get_header(head_hash)
            if head is None:
                raise ForkChoiceError("unknown head block")
            fin = None
            for name, h in (("safe", safe_hash),
                            ("finalized", finalized_hash)):
                if h:
                    hdr = store.get_header(h)
                    if hdr is None:
                        raise ForkChoiceError(f"unknown {name} block")
                    if not _is_ancestor(store, hdr, head):
                        raise InvalidForkChoiceState(
                            f"{name} block 0x{h.hex()} is not an "
                            f"ancestor of the new head")
                    if name == "finalized":
                        fin = hdr

            # finish any reorg transition a crash interrupted before
            # starting a new one (idempotent; usually a no-op)
            self.recover_pending()

            # collect the branch from head back to a canonical ancestor
            branch = []
            cursor = head
            while store.canonical_hash(cursor.number) != cursor.hash:
                branch.append(cursor)
                parent = store.get_header(cursor.parent_hash)
                if parent is None:
                    raise ForkChoiceError("detached branch")
                cursor = parent
            old_head = store.head_header()
            # orphaned = formerly-canonical blocks above the common
            # ancestor: heights the branch overwrites plus any stale
            # heights above the new head (a head rollback)
            pivot = cursor.number
            orphaned = []
            for number in range(pivot + 1, old_head.number + 1):
                h = store.canonical_hash(number)
                blk = store.get_block(h) if h else None
                if blk is not None and h != head_hash \
                        and all(h != b.hash for b in branch):
                    orphaned.append(blk)
            adopted = [blk for blk in
                       (store.get_block(b.hash) for b in reversed(branch))
                       if blk is not None]
            adopted_tx = {tx.hash for blk in adopted
                          for tx in blk.body.transactions}

            # chaos seat, leg 1: crash BEFORE the canonical rewrite —
            # the old index must be fully intact
            inject("forkchoice.apply")

            # the canonical+txloc rewrite, head/safe/finalized markers
            # and the pending-reorg journal commit as ONE journaled
            # unit: a crash at any byte offset leaves either the old
            # chain or the new chain with its mempool debt recorded
            with store.write_group():
                for number in range(head.number + 1, old_head.number + 1):
                    store.delete_canonical(number)
                for header in branch:
                    store.set_canonical(header.number, header.hash)
                store.set_head(head_hash)
                if safe_hash:
                    store.meta["safe"] = safe_hash
                if finalized_hash:
                    store.meta["finalized"] = finalized_hash
                    # flatten every layer at or below the finalized
                    # height to the durable backend
                    store.finalize_node_layers(fin.number)
                # tx locations follow the canonical index in the same
                # group: adopted inclusions point at their new blocks,
                # orphaned-only inclusions are pruned — RPC can never
                # serve an orphaned inclusion
                for blk in adopted:
                    for i, tx in enumerate(blk.body.transactions):
                        store.set_tx_location(tx.hash, blk.hash, i)
                for blk in orphaned:
                    for tx in blk.body.transactions:
                        if tx.hash not in adopted_tx:
                            store.delete_tx_location(tx.hash)
                if orphaned and self.mempool is not None:
                    store.meta[REORG_JOURNAL_KEY] = b"".join(
                        b.hash for b in orphaned)

            # chaos seat, leg 2: crash AFTER the rewrite committed but
            # before the mempool settles — recovery replays it from the
            # journal (pair with after=1 to target this leg)
            inject("forkchoice.apply")

            outcome = ReorgOutcome(head, adopted, orphaned)
            if orphaned:
                self._settle(outcome)
            elif self.mempool is not None and adopted_tx:
                # plain adoption (engine newPayload -> fcU of externally
                # built blocks): drop pool copies of the adopted txs so
                # a tx is never pending and included at once — not a
                # reorg, so no reorg metrics fire
                for blk in adopted:
                    for tx in blk.body.transactions:
                        if self.mempool.get_transaction(tx.hash) is not None:
                            self.mempool.remove_transaction(
                                tx.hash, reason="included")
                            outcome.evicted += 1
            return outcome

    # -- crash recovery ----------------------------------------------------
    def recover_pending(self) -> ReorgOutcome | None:
        """Replay the mempool leg of a reorg whose canonical rewrite
        committed but whose settlement was interrupted (process crash
        or an injected fault between the two legs).  Idempotent: txs
        already back in the pool or canonically re-included are
        skipped.  Run on node start and at the top of every apply."""
        if self.mempool is None:
            return None
        with self.lock:
            raw = self.store.meta.get(REORG_JOURNAL_KEY)
            if not raw:
                return None
            hashes = [raw[i:i + 32] for i in range(0, len(raw), 32)]
            orphaned = [blk for blk in
                        (self.store.get_block(h) for h in hashes)
                        if blk is not None]
            outcome = ReorgOutcome(self.store.head_header(), [], orphaned,
                                   recovered=True)
            self.recoveries += 1
            self._settle(outcome, count_reorg=False)
            return outcome

    # -- the mempool leg ---------------------------------------------------
    def _settle(self, outcome: ReorgOutcome, count_reorg: bool = True):
        """Re-inject, evict, revalidate, clear the journal, notify.
        Runs with self.lock held (apply) or standalone (recovery)."""
        store = self.store
        if count_reorg:
            record_chain_reorg(outcome.depth)
            self.reorgs += 1
            self.last_depth = outcome.depth
            self.deepest = max(self.deepest, outcome.depth)
        mp = self.mempool
        if mp is not None:
            head = outcome.head
            # 1. re-inject orphaned txs that did not land on the new
            #    canonical branch (canonical_tx_location is the truth:
            #    it also filters re-adoptions below the pivot and makes
            #    the recovery replay idempotent)
            for blk in outcome.orphaned:
                for tx in blk.body.transactions:
                    if store.canonical_tx_location(tx.hash) is not None:
                        continue
                    if tx.tx_type == TYPE_BLOB:
                        # the blob sidecar died with the orphaned
                        # inclusion; without it the tx cannot be
                        # re-broadcast — count the loss truthfully
                        # instead of re-injecting an unprovable tx
                        self._count_eviction("blob_unrecoverable")
                        outcome.evicted += 1
                        continue
                    if mp.reinject(tx):
                        outcome.reinjected += 1
                        self.reinjected += 1
            # 2. evict pool entries the new branch adopted
            for blk in outcome.adopted:
                for tx in blk.body.transactions:
                    if mp.get_transaction(tx.hash) is not None:
                        mp.remove_transaction(tx.hash, reason="included")
                        self._count_eviction("adopted")
                        outcome.evicted += 1
            # 3. revalidate the surviving pool against the new state
            root = head.state_root

            def get_account(address):
                return store.account_state(root, address)

            outcome.pruned = mp.revalidate(get_account)
            for reason, n in outcome.pruned.items():
                for _ in range(n):
                    self._count_eviction(reason)
                outcome.evicted += n
            # the mempool debt is paid: clear the journal (its own
            # group — it must commit strictly after the settlement ran)
            with store.write_group():
                store.meta.pop(REORG_JOURNAL_KEY, None)
        for listener in list(self.listeners):
            try:
                listener(outcome)
            except Exception:  # noqa: BLE001 — observers must not fail us
                pass

    def _count_eviction(self, reason: str):
        self.evictions[reason] = self.evictions.get(reason, 0) + 1
        record_mempool_reorg_eviction(reason)

    # -- observability -----------------------------------------------------
    def stats_json(self) -> dict:
        return {
            "reorgs": self.reorgs,
            "lastDepth": self.last_depth,
            "deepestDepth": self.deepest,
            "reinjected": self.reinjected,
            "evictions": dict(sorted(self.evictions.items())),
            "recoveries": self.recoveries,
            "pendingJournal": bool(
                self.store.meta.get(REORG_JOURNAL_KEY)),
        }


def apply_fork_choice(store: Store, head_hash: bytes,
                      safe_hash: bytes = b"", finalized_hash: bytes = b""):
    """Store-only fork choice (no mempool wired): rewrite the canonical
    + txloc indices and markers.  Returns the new head header.  Node
    paths go through Node.reorg_handler so the pool settles too."""
    return ReorgHandler(store).apply(
        head_hash, safe_hash, finalized_hash).head
