"""Speculative mempool prewarming (the seat of the reference's
crates/blockchain/prewarm.rs): during the idle gap between blocks, run
pending transactions against a THROWAWAY state layered on the head root
and discard every result.  The side effect is the point — account/storage
trie paths, contract code and persistent-backend pages are pulled into
the node/code table caches, so the real block build hits warm caches.

Differences from the reference, by architecture: the reference prewarms
on rayon workers inside the node process; here the producer loop calls
`prewarm_transactions` in its idle window (Node._producer_loop), and the
warmed state is the Store's table caches (the persistent backend's read
cache when --datadir is set; the shared in-memory tables otherwise) —
the StateDB scratch layer itself is dropped.

Senders are batch-recovered up front (`sender_recovery.recover_senders`)
so speculative runs reuse one recovery per tx instead of re-deriving
inline — and the caches seeded here survive into the real block build.
"""

from __future__ import annotations

import time

from ..evm.db import StateDB
from ..evm.executor import execute_tx
from ..evm.vm import BlockEnv
from . import sender_recovery


class _DeadlineAbort(Exception):
    """Raised by the deadline tracer to bail out of a long tx run."""


class _DeadlineTracer:
    """Frame-boundary deadline guard for speculative runs.

    Checks the clock on every call-frame enter/exit — cheap (no per-step
    hook, so the native dispatch loop stays active) yet bounds how long a
    call-heavy tx can overrun the idle window.  A single hot frame with
    no sub-calls still runs to completion; the producer loop's own
    deadline check between txs is the backstop for those.
    """

    __slots__ = ("deadline",)

    def __init__(self, deadline: float):
        self.deadline = deadline

    def enter(self, msg):
        if time.monotonic() >= self.deadline:
            raise _DeadlineAbort

    def exit(self, ok, gas_left, out):
        if time.monotonic() >= self.deadline:
            raise _DeadlineAbort


def prewarm_transactions(chain, parent_header, txs,
                         deadline: float | None = None,
                         max_txs: int = 256) -> int:
    """Speculatively execute up to `max_txs` transactions against the
    parent state; returns how many ran.  Never mutates canonical state
    (scratch StateDB, discarded) and never raises — a failing tx is
    skipped and warming continues with the next one.  Past `deadline`
    (checked between txs and at call-frame boundaries inside them) the
    pass stops."""
    from ..storage.store import StoreSource

    if not txs:
        return 0
    try:
        source = StoreSource(chain.store, parent_header.state_root)
    except Exception:
        return 0
    txs = txs[:max_txs]
    try:
        # one batched recovery instead of per-run inline derivation; the
        # seeded caches are reused by the real block build afterwards
        sender_recovery.recover_senders(txs)
    except Exception:
        pass  # speculation only; inline recovery remains the backstop
    state = StateDB(source)
    env = BlockEnv(
        number=parent_header.number + 1,
        coinbase=parent_header.coinbase,
        timestamp=parent_header.timestamp + 1,
        gas_limit=parent_header.gas_limit,
        base_fee=parent_header.base_fee_per_gas or 0,
        excess_blob_gas=parent_header.excess_blob_gas or 0,
        prev_randao=parent_header.prev_randao or b"\x00" * 32,
    )
    tracer = _DeadlineTracer(deadline) if deadline is not None else None
    ran = 0
    for tx in txs:
        if deadline is not None and time.monotonic() >= deadline:
            break
        try:
            execute_tx(tx, state, env, chain.config, tracer=tracer)
            ran += 1
        except _DeadlineAbort:
            break
        except Exception:
            # speculation only: any failure (InvalidTransaction or a bug
            # surfaced by a malformed tx) just skips this tx; later txs
            # still warm their lanes
            continue
    return ran
