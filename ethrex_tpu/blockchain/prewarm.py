"""Speculative mempool prewarming (the seat of the reference's
crates/blockchain/prewarm.rs): during the idle gap between blocks, run
pending transactions against a THROWAWAY state layered on the head root
and discard every result.  The side effect is the point — account/storage
trie paths, contract code and persistent-backend pages are pulled into
the node/code table caches, so the real block build hits warm caches.

Differences from the reference, by architecture: the reference prewarms
on rayon workers inside the node process; here the producer loop calls
`prewarm_transactions` in its idle window (Node._producer_loop), and the
warmed state is the Store's table caches (the persistent backend's read
cache when --datadir is set; the shared in-memory tables otherwise) —
the StateDB scratch layer itself is dropped.
"""

from __future__ import annotations

import time

from ..evm.db import StateDB
from ..evm.executor import execute_tx
from ..evm.vm import BlockEnv


def prewarm_transactions(chain, parent_header, txs,
                         deadline: float | None = None,
                         max_txs: int = 256) -> int:
    """Speculatively execute up to `max_txs` transactions against the
    parent state; returns how many ran.  Never mutates canonical state
    (scratch StateDB, discarded) and never raises — a failing tx just
    stops warming that sender's lane."""
    from ..storage.store import StoreSource

    if not txs:
        return 0
    try:
        source = StoreSource(chain.store, parent_header.state_root)
    except Exception:
        return 0
    state = StateDB(source)
    env = BlockEnv(
        number=parent_header.number + 1,
        coinbase=parent_header.coinbase,
        timestamp=parent_header.timestamp + 1,
        gas_limit=parent_header.gas_limit,
        base_fee=parent_header.base_fee_per_gas or 0,
        excess_blob_gas=parent_header.excess_blob_gas or 0,
        prev_randao=parent_header.prev_randao or b"\x00" * 32,
    )
    ran = 0
    for tx in txs[:max_txs]:
        if deadline is not None and time.monotonic() >= deadline:
            break
        try:
            execute_tx(tx, state, env, chain.config)
            ran += 1
        except Exception:
            # speculation only: any failure (InvalidTransaction or a bug
            # surfaced by a malformed tx) just skips this warm lane
            continue
    return ran
