"""Payload building: create a block skeleton, fill it from the mempool,
finalize roots (parity with the reference's crates/blockchain/payload.rs
create_payload/build_payload/fill_transactions/finalize_payload)."""

from __future__ import annotations

import dataclasses
import time

from ..primitives.block import (Block, BlockBody, BlockHeader, ZERO_HASH,
                                ZERO_NONCE)
from ..primitives.transaction import TYPE_PRIVILEGED
from ..primitives.genesis import Fork
from ..primitives.receipt import Receipt, logs_bloom
from ..evm import gas as G
from ..evm.db import StateDB
from ..evm.executor import InvalidTransaction, execute_tx
from ..evm.vm import BlockEnv
from .blockchain import (Blockchain, compute_receipts_root,
                         compute_requests_hash, compute_tx_root,
                         compute_withdrawals_root, next_base_fee)


@dataclasses.dataclass
class PayloadBuildResult:
    block: Block
    receipts: list
    state_db: StateDB
    fees_collected: int = 0


def create_payload_header(parent: BlockHeader, config, *, timestamp: int,
                          coinbase: bytes, prev_randao: bytes = ZERO_HASH,
                          gas_limit: int | None = None,
                          extra_data: bytes = b"") -> BlockHeader:
    fork = config.fork_at(parent.number + 1, timestamp)
    h = BlockHeader(
        parent_hash=parent.hash, coinbase=coinbase,
        number=parent.number + 1,
        gas_limit=gas_limit or parent.gas_limit,
        timestamp=timestamp, extra_data=extra_data,
        prev_randao=prev_randao, nonce=ZERO_NONCE, difficulty=0,
    )
    if fork >= Fork.LONDON:
        h.base_fee_per_gas = next_base_fee(parent)
    if fork >= Fork.SHANGHAI:
        h.withdrawals_root = None  # filled at finalize
    if fork >= Fork.CANCUN:
        target, max_bg, fraction = config.blob_params_at(timestamp)
        h.excess_blob_gas = G.calc_excess_blob_gas(
            parent.excess_blob_gas or 0, parent.blob_gas_used or 0,
            target, max_bg, fraction,
            parent_base_fee=parent.base_fee_per_gas or 0,
            eip7918=fork >= Fork.OSAKA)
    return h


def build_payload(chain: Blockchain, parent: BlockHeader,
                  header: BlockHeader, txs: list, withdrawals: list,
                  parent_beacon_block_root: bytes = ZERO_HASH,
                  mempool=None) -> PayloadBuildResult:
    """Execute txs on top of parent and finalize a full block.

    txs: ordered candidate transactions; invalid ones are skipped (and
    dropped from `mempool` if given) rather than failing the build.

    The build is decomposed into profiler stage spans under component
    ``payload`` (select / execute / merkleize / seal; drain and prewarm
    are recorded by the producer around this call) so the producer has
    the same stage breakdown the prover has — the chain-path X-ray
    reads it to say where a slow block spent its wall.
    """
    from ..perf.profiler import record_stage

    config = chain.config
    fork = config.fork_at(header.number, header.timestamp)
    env = BlockEnv(
        number=header.number, coinbase=header.coinbase,
        timestamp=header.timestamp, gas_limit=header.gas_limit,
        prev_randao=header.prev_randao,
        base_fee=header.base_fee_per_gas or 0,
        excess_blob_gas=header.excess_blob_gas or 0,
        parent_beacon_block_root=parent_beacon_block_root,
    )
    state = chain.store.state_db(parent.state_root)
    chain._pre_tx_system_ops(state, env, dataclasses.replace(
        header, parent_beacon_block_root=parent_beacon_block_root), fork)

    receipts = []
    included = []
    gas_used = 0
    blob_gas = 0
    fees = 0
    _, max_blob_gas, _ = config.blob_params_at(header.timestamp)
    select_s = 0.0
    execute_s = 0.0
    clock = time.monotonic
    for tx in txs:
        t_sel = clock()
        if gas_used + tx.gas_limit > header.gas_limit:
            select_s += clock() - t_sel
            continue
        tx_blob_gas = G.BLOB_GAS_PER_BLOB * len(tx.blob_versioned_hashes)
        if blob_gas + tx_blob_gas > max_blob_gas:
            select_s += clock() - t_sel
            continue
        t_exec = clock()
        select_s += t_exec - t_sel
        try:
            result = execute_tx(tx, state, env, config)
        except InvalidTransaction:
            execute_s += clock() - t_exec
            if mempool is not None:
                mempool.remove_transaction(tx.hash,
                                           reason="invalid_at_build")
            continue
        execute_s += clock() - t_exec
        gas_used += result.gas_used
        blob_gas += tx_blob_gas
        if tx.tx_type != TYPE_PRIVILEGED:
            tip = (tx.effective_gas_price(env.base_fee) or 0) - env.base_fee
            fees += result.gas_used * tip
        included.append(tx)
        receipts.append(Receipt(
            tx_type=tx.tx_type, succeeded=result.success,
            cumulative_gas_used=gas_used, logs=result.logs))

    t_seal = clock()
    for wd in withdrawals or []:
        if wd.amount:
            state.begin_tx()
            state.add_balance(wd.address, wd.amount * 10**9)
            state.finalize_tx()
    requests = chain._post_tx_requests(state, env, receipts, fork)

    header = dataclasses.replace(header)
    header.gas_used = gas_used
    t_merk = clock()
    header.tx_root = compute_tx_root(included)
    header.receipts_root = compute_receipts_root(receipts)
    header.bloom = logs_bloom([l for r in receipts for l in r.logs])
    merkleize_s = clock() - t_merk
    if fork >= Fork.SHANGHAI:
        header.withdrawals_root = compute_withdrawals_root(withdrawals or [])
    if fork >= Fork.CANCUN:
        header.blob_gas_used = blob_gas
        header.parent_beacon_block_root = parent_beacon_block_root
    if fork >= Fork.PRAGUE:
        header.requests_hash = compute_requests_hash(requests)
    t_merk = clock()
    header.state_root = chain.store.apply_account_updates(
        parent.state_root, state)
    merkleize_s += clock() - t_merk
    body = BlockBody(
        transactions=included, uncles=[],
        withdrawals=list(withdrawals or [])
        if fork >= Fork.SHANGHAI else None,
    )
    record_stage("payload", "select", select_s)
    record_stage("payload", "execute", execute_s)
    record_stage("payload", "merkleize", merkleize_s)
    record_stage("payload", "seal", clock() - t_seal - merkleize_s)
    return PayloadBuildResult(block=Block(header, body), receipts=receipts,
                              state_db=state, fees_collected=fees)
