"""Transaction pool (parity with the reference's
crates/blockchain/mempool.rs: per-account queues, tip ordering, replacement,
blob support; simplified admission rules for round 1)."""

from __future__ import annotations

import threading

from ..primitives.transaction import TYPE_BLOB, Transaction

MIN_REPLACEMENT_BUMP = 10  # percent


class MempoolError(Exception):
    pass


MAX_BLOB_MEMPOOL_SIZE = 512   # reference: mempool.rs:49


class Mempool:
    def __init__(self, capacity: int = 10_000,
                 blob_capacity: int = MAX_BLOB_MEMPOOL_SIZE):
        self.capacity = capacity
        self.blob_capacity = blob_capacity
        self.by_hash: dict[bytes, Transaction] = {}
        self.by_sender: dict[bytes, dict[int, Transaction]] = {}
        self.blobs_bundles: dict[bytes, object] = {}  # tx_hash -> bundle
        # arrival order of REGULAR (non-blob) txs: the FIFO eviction
        # queue (reference: mempool.rs txs_order +
        # remove_oldest_regular_transaction:462-475); stale entries for
        # already-removed txs are skipped at pop time
        self.txs_order: list[bytes] = []
        self.lock = threading.RLock()
        # arrival hooks (e.g. pending-tx RPC filters); invoked OUTSIDE
        # self.lock so subscribers may take their own locks freely
        self.on_add: list = []

    def add_transaction(self, tx: Transaction, sender_nonce: int,
                        sender_balance: int, base_fee: int,
                        blobs_bundle=None) -> bytes:
        from ..primitives.transaction import TYPE_PRIVILEGED

        if tx.tx_type == TYPE_PRIVILEGED:
            raise MempoolError("privileged txs bypass the mempool")
        sender = tx.sender()
        if sender is None:
            raise MempoolError("invalid signature")
        if tx.nonce < sender_nonce:
            raise MempoolError("nonce too low")
        if tx.gas_limit * tx.max_fee() + tx.value > sender_balance:
            raise MempoolError("insufficient funds")
        if tx.tx_type == TYPE_BLOB and blobs_bundle is None:
            raise MempoolError("blob tx requires blobs bundle")
        with self.lock:
            queue = self.by_sender.setdefault(sender, {})
            existing = queue.get(tx.nonce)
            if existing is not None:
                bump = existing.max_fee() * (100 + MIN_REPLACEMENT_BUMP) // 100
                if tx.max_fee() < bump:
                    raise MempoolError("replacement underpriced")
                self.by_hash.pop(existing.hash, None)
                self.blobs_bundles.pop(existing.hash, None)
            queue[tx.nonce] = tx
            self.by_hash[tx.hash] = tx
            if blobs_bundle is not None:
                self.blobs_bundles[tx.hash] = blobs_bundle
                self._evict_worst_blob()
            else:
                self.txs_order.append(tx.hash)
                self._evict_oldest_regular()
                # amortized compaction: stale entries (mined/replaced
                # txs) are skipped at pop time, but the list must stay
                # bounded on a long-running node (review finding; the
                # reference's mempool_prune_threshold seat)
                if len(self.txs_order) > 2 * self.capacity + 1024:
                    self.txs_order = [
                        h for h in self.txs_order
                        if h in self.by_hash
                        and h not in self.blobs_bundles]
        for hook in list(self.on_add):
            hook(tx.hash)
        return tx.hash

    def _regular_tx_count(self) -> int:
        return len(self.by_hash) - len(self.blobs_bundles)

    def _evict_oldest_regular(self) -> None:
        """FIFO-evict regular txs past the cap; blob txs never feel
        regular-pool pressure (reference: mempool.rs:462-475)."""
        while self._regular_tx_count() > self.capacity and self.txs_order:
            oldest = self.txs_order.pop(0)
            if oldest in self.by_hash and oldest not in self.blobs_bundles:
                self._remove_locked(oldest)

    def _evict_worst_blob(self) -> None:
        """Evict the LEAST INCLUDABLE blob tx past the blob sub-pool cap:
        deepest per-sender nonce offset first (it cannot be included
        until earlier same-sender blobs clear), ties broken by lowest
        blob fee (reference: mempool.rs:477-530)."""
        while len(self.blobs_bundles) > self.blob_capacity:
            min_nonce: dict[bytes, int] = {}
            for h in self.blobs_bundles:
                tx = self.by_hash.get(h)
                if tx is None:
                    continue
                s = tx.sender()
                if s not in min_nonce or tx.nonce < min_nonce[s]:
                    min_nonce[s] = tx.nonce
            worst = None
            worst_key = None
            for h in self.blobs_bundles:
                tx = self.by_hash.get(h)
                if tx is None:
                    continue
                offset = tx.nonce - min_nonce[tx.sender()]
                key = (offset, -(tx.max_fee_per_blob_gas or 0))
                if worst_key is None or key > worst_key:
                    worst_key = key
                    worst = h
            if worst is None:
                break
            self._remove_locked(worst)

    def _remove_locked(self, tx_hash: bytes):
        tx = self.by_hash.pop(tx_hash, None)
        if tx is None:
            return
        self.blobs_bundles.pop(tx_hash, None)
        sender = tx.sender()
        queue = self.by_sender.get(sender)
        if queue and queue.get(tx.nonce) is tx:
            del queue[tx.nonce]
            if not queue:
                del self.by_sender[sender]

    def remove_transaction(self, tx_hash: bytes):
        with self.lock:
            self._remove_locked(tx_hash)

    def get_transaction(self, tx_hash: bytes) -> Transaction | None:
        return self.by_hash.get(tx_hash)

    def pending(self, base_fee: int, get_nonce) -> list[Transaction]:
        """Executable txs in inclusion order: highest effective tip first,
        but never breaking per-sender nonce order — a heap over each
        sender's *next* executable tx (the reference's fill_transactions
        ordering, crates/blockchain/payload.rs)."""
        import heapq

        with self.lock:
            chains: dict[bytes, list[Transaction]] = {}
            for sender, queue in self.by_sender.items():
                nonce = get_nonce(sender)
                run = []
                while nonce in queue:
                    tx = queue[nonce]
                    if tx.effective_gas_price(base_fee) is None:
                        break
                    run.append(tx)
                    nonce += 1
                if run:
                    chains[sender] = run
            heap = []
            for seq, (sender, run) in enumerate(chains.items()):
                tip = run[0].effective_gas_price(base_fee) - base_fee
                heapq.heappush(heap, (-tip, seq, sender, 0))
            out = []
            while heap:
                _, seq, sender, idx = heapq.heappop(heap)
                run = chains[sender]
                out.append(run[idx])
                if idx + 1 < len(run):
                    tip = run[idx + 1].effective_gas_price(base_fee) - base_fee
                    heapq.heappush(heap, (-tip, seq, sender, idx + 1))
            return out

    def content(self) -> dict:
        with self.lock:
            return {
                sender: dict(queue)
                for sender, queue in self.by_sender.items()
            }

    def split(self, get_nonce) -> tuple[dict, dict]:
        """The pending-vs-queued partition (reference mempool / geth txpool
        semantics): per sender, txs forming a contiguous nonce run from the
        account's current nonce are PENDING (executable); gapped/future
        nonces are QUEUED until the gap fills."""
        with self.lock:
            pending: dict[bytes, dict[int, Transaction]] = {}
            queued: dict[bytes, dict[int, Transaction]] = {}
            for sender, queue in self.by_sender.items():
                nonce = get_nonce(sender)
                run = {}
                while nonce in queue:
                    run[nonce] = queue[nonce]
                    nonce += 1
                rest = {n: tx for n, tx in queue.items() if n not in run}
                if run:
                    pending[sender] = run
                if rest:
                    queued[sender] = rest
            return pending, queued

    def status(self, get_nonce) -> dict:
        pending, queued = self.split(get_nonce)
        return {
            "pending": sum(len(q) for q in pending.values()),
            "queued": sum(len(q) for q in queued.values()),
        }

    def __len__(self):
        return len(self.by_hash)
