"""Transaction pool (parity with the reference's
crates/blockchain/mempool.rs: per-account queues, tip ordering, replacement,
blob support; simplified admission rules for round 1)."""

from __future__ import annotations

import threading
import time

from ..primitives.transaction import TYPE_BLOB, Transaction
from ..utils.faults import inject
from ..utils.metrics import (record_mempool_admission,
                             record_mempool_eviction,
                             record_mempool_occupancy,
                             record_mempool_reinjection,
                             record_mempool_rejection,
                             record_mempool_replacement,
                             observe_time_in_pool)

# chain-path X-ray (perf/chain_path.py): mempool admission is the first
# measured stage queue of the tx pipeline.  Guarded import + never-raise
# hooks — a perf-layer failure must not break the pool.
try:
    from ..perf.chain_path import CHAIN_PATH as _CHAIN_PATH
except Exception:  # pragma: no cover - telemetry only
    _CHAIN_PATH = None

MIN_REPLACEMENT_BUMP = 10  # percent

# admission-control defaults (docs/OVERLOAD.md "Mempool admission"):
# per-sender slot cap and nonce-gap limit bound what one adversarial
# key can pin in the pool; the dynamic fee floor starts rising at
# FEE_FLOOR_START utilization and reaches FEE_FLOOR_MAX_MULTIPLE x
# base_fee at 100%, so `pool_full` becomes a priced signal instead of
# an eviction scramble
MAX_SENDER_SLOTS = 64
MAX_NONCE_GAP = 64
FEE_FLOOR_START = 0.85
FEE_FLOOR_MAX_MULTIPLE = 10.0


class MempoolError(Exception):
    """Admission failure.  Subclasses carry a machine-readable ``reason``
    label so rejection counters are labelled truthfully; the message
    strings are part of the RPC error surface and stay unchanged."""

    reason = "other"


class PrivilegedTxError(MempoolError):
    reason = "privileged"


class InvalidSignatureError(MempoolError):
    reason = "invalid_signature"


class NonceTooLowError(MempoolError):
    reason = "nonce_too_low"


class InsufficientFundsError(MempoolError):
    reason = "insufficient_funds"


class BlobsMissingError(MempoolError):
    reason = "blobs_missing"


class UnderpricedError(MempoolError):
    reason = "underpriced"


class ReplacementUnderpricedError(UnderpricedError):
    """Typed replacement-by-fee rejection: same sender+nonce without the
    >=10% effective-fee bump.  Subclasses UnderpricedError and keeps its
    reason label and message, so the legacy rejection ledger and RPC
    error surface stay byte-identical while callers can catch the
    replacement case specifically."""


class NonceGapError(MempoolError):
    reason = "nonce_gap"


class SenderLimitError(MempoolError):
    reason = "sender_limit"


class FeeBelowFloorError(MempoolError):
    reason = "fee_below_floor"


MAX_BLOB_MEMPOOL_SIZE = 512   # reference: mempool.rs:49


class Mempool:
    def __init__(self, capacity: int = 10_000,
                 blob_capacity: int = MAX_BLOB_MEMPOOL_SIZE,
                 max_sender_slots: int = MAX_SENDER_SLOTS,
                 max_nonce_gap: int = MAX_NONCE_GAP,
                 fee_floor_start: float = FEE_FLOOR_START,
                 fee_floor_max_multiple: float = FEE_FLOOR_MAX_MULTIPLE):
        self.capacity = capacity
        self.blob_capacity = blob_capacity
        self.max_sender_slots = max_sender_slots
        self.max_nonce_gap = max_nonce_gap
        self.fee_floor_start = fee_floor_start
        self.fee_floor_max_multiple = fee_floor_max_multiple
        self.by_hash: dict[bytes, Transaction] = {}
        self.by_sender: dict[bytes, dict[int, Transaction]] = {}
        self.blobs_bundles: dict[bytes, object] = {}  # tx_hash -> bundle
        # arrival order of REGULAR (non-blob) txs: the FIFO eviction
        # queue (reference: mempool.rs txs_order +
        # remove_oldest_regular_transaction:462-475); stale entries for
        # already-removed txs are skipped at pop time
        self.txs_order: list[bytes] = []
        self.lock = threading.RLock()
        # arrival hooks (e.g. pending-tx RPC filters); invoked OUTSIDE
        # self.lock so subscribers may take their own locks freely
        self.on_add: list = []
        # flow accounting (pool-local so ethrex_health survives metric
        # registry resets): admission timestamps for the time-in-pool
        # histogram, plus admission/rejection/eviction tallies
        self.added_at: dict[bytes, float] = {}
        self.admitted = 0
        self.replacements = 0
        self.reinjections = 0
        self.rejections: dict[str, int] = {}
        self.evictions: dict[str, int] = {}

    def _reject(self, err: MempoolError) -> MempoolError:
        with self.lock:
            self.rejections[err.reason] = \
                self.rejections.get(err.reason, 0) + 1
        record_mempool_rejection(err.reason)
        return err

    def _utilization(self) -> float:
        blob = len(self.blobs_bundles)
        regular = len(self.by_hash) - blob
        return max(regular / self.capacity if self.capacity else 0.0,
                   blob / self.blob_capacity if self.blob_capacity else 0.0)

    def _publish_occupancy_locked(self) -> None:
        record_mempool_occupancy(len(self.by_hash), self._utilization())

    def utilization(self) -> float:
        """Current fill fraction (max of the regular and blob
        sub-pools); the RPC shed-level mempool feedback reads this."""
        with self.lock:
            return self._utilization()

    def _fee_floor_locked(self, base_fee: int) -> int:
        regular = len(self.by_hash) - len(self.blobs_bundles)
        util = regular / self.capacity if self.capacity else 0.0
        if util < self.fee_floor_start:
            return 0
        span = (util - self.fee_floor_start) / \
            max(1e-9, 1.0 - self.fee_floor_start)
        mult = 1.0 + (self.fee_floor_max_multiple - 1.0) * min(1.0, span)
        return int(max(base_fee, 1) * mult)

    def fee_floor(self, base_fee: int) -> int:
        """Dynamic admission fee floor for NEW regular slots: 0 while
        the regular pool sits below ``fee_floor_start`` utilization,
        then a linear ramp to ``fee_floor_max_multiple`` x base_fee at
        100% — a full pool prices admission instead of churning its
        FIFO eviction queue.  Replacements are exempt (they do not grow
        the pool); so are blob txs (the blob sub-pool has its own
        least-includable eviction rules)."""
        with self.lock:
            return self._fee_floor_locked(base_fee)

    def add_transaction(self, tx: Transaction, sender_nonce: int,
                        sender_balance: int, base_fee: int,
                        blobs_bundle=None) -> bytes:
        from ..primitives.transaction import TYPE_PRIVILEGED

        # chaos seat: a slow or crashing admission path (fired OUTSIDE
        # self.lock so an injected delay cannot serialize the pool)
        inject("mempool.add")
        if tx.tx_type == TYPE_PRIVILEGED:
            raise self._reject(
                PrivilegedTxError("privileged txs bypass the mempool"))
        sender = tx.sender()
        if sender is None:
            raise self._reject(InvalidSignatureError("invalid signature"))
        if tx.nonce < sender_nonce:
            raise self._reject(NonceTooLowError("nonce too low"))
        if tx.gas_limit * tx.max_fee() + tx.value > sender_balance:
            raise self._reject(InsufficientFundsError("insufficient funds"))
        if tx.tx_type == TYPE_BLOB and blobs_bundle is None:
            raise self._reject(
                BlobsMissingError("blob tx requires blobs bundle"))
        with self.lock:
            existing_queue = self.by_sender.get(sender)
            existing = existing_queue.get(tx.nonce) if existing_queue \
                else None
            if existing is not None:
                # replacement-by-fee: exempt from the sender cap, the
                # gap limit and the fee floor — it does not grow the
                # pool — but must clear the >=10% effective-fee bump
                bump = existing.max_fee() * (100 + MIN_REPLACEMENT_BUMP) // 100
                if tx.max_fee() < bump:
                    raise self._reject(
                        ReplacementUnderpricedError(
                            "replacement underpriced"))
                dwell = self._dwell_locked(existing.hash)
                self.by_hash.pop(existing.hash, None)
                self.blobs_bundles.pop(existing.hash, None)
                self.added_at.pop(existing.hash, None)
                self.evictions["replaced"] = \
                    self.evictions.get("replaced", 0) + 1
                record_mempool_eviction("replaced")
                if dwell is not None:
                    observe_time_in_pool(dwell, "replaced")
                if _CHAIN_PATH is not None:
                    _CHAIN_PATH.tx_removed(existing.hash, "replaced",
                                           dwell)
                self.replacements += 1
                record_mempool_replacement()
            else:
                # NEW-slot admission rules (docs/OVERLOAD.md): bound
                # what one key can pin, refuse unreachable nonces, and
                # price admission when the regular pool runs hot
                if tx.nonce - sender_nonce > self.max_nonce_gap:
                    raise self._reject(NonceGapError(
                        f"nonce gap {tx.nonce - sender_nonce} exceeds "
                        f"limit {self.max_nonce_gap}"))
                if existing_queue is not None and \
                        len(existing_queue) >= self.max_sender_slots:
                    raise self._reject(SenderLimitError(
                        f"sender already holds {len(existing_queue)} "
                        f"txs (cap {self.max_sender_slots})"))
                if blobs_bundle is None:
                    floor = self._fee_floor_locked(base_fee)
                    if floor and tx.max_fee() < floor:
                        raise self._reject(FeeBelowFloorError(
                            f"max fee {tx.max_fee()} below dynamic "
                            f"floor {floor}"))
            queue = self.by_sender.setdefault(sender, {})
            queue[tx.nonce] = tx
            self.by_hash[tx.hash] = tx
            self.added_at[tx.hash] = time.monotonic()
            if blobs_bundle is not None:
                self.blobs_bundles[tx.hash] = blobs_bundle
                self._evict_worst_blob()
            else:
                self.txs_order.append(tx.hash)
                self._evict_oldest_regular()
                # amortized compaction: stale entries (mined/replaced
                # txs) are skipped at pop time, but the list must stay
                # bounded on a long-running node (review finding; the
                # reference's mempool_prune_threshold seat)
                if len(self.txs_order) > 2 * self.capacity + 1024:
                    self.txs_order = [
                        h for h in self.txs_order
                        if h in self.by_hash
                        and h not in self.blobs_bundles]
            # a full blob sub-pool may pick the INCOMING tx as its own
            # least-includable eviction victim: admission succeeded
            # (pinned behavior — the hash is returned) but the pool is
            # effectively full for it, so count it truthfully
            admitted_ok = False
            if tx.hash not in self.by_hash:
                self.rejections["pool_full"] = \
                    self.rejections.get("pool_full", 0) + 1
                record_mempool_rejection("pool_full")
            else:
                self.admitted += 1
                record_mempool_admission()
                admitted_ok = True
            self._publish_occupancy_locked()
        # chain-path admission arrival (and a sampled lifecycle record)
        # fires outside the lock, like the on_add hooks
        if admitted_ok and _CHAIN_PATH is not None:
            _CHAIN_PATH.tx_admitted(tx.hash)
        for hook in list(self.on_add):
            hook(tx.hash)
        return tx.hash

    def reinject(self, tx: Transaction, blobs_bundle=None) -> bool:
        """Typed reorg re-injection path (docs/CHAIN_RESILIENCE.md): the
        tx was already admitted once and included on a now-orphaned
        block, so the fee floor, sender cap and nonce-gap rules do NOT
        apply — dropping it at admission would silently lose an
        accepted transaction, breaking the reorg conservation
        invariant.  Capacity still binds (FIFO eviction keeps the pool
        bounded) and the ReorgHandler's revalidation pass prunes
        entries the new canonical state invalidated.  Returns True if
        the tx entered the pool; False for duplicates, an occupied
        sender+nonce slot (the pool's entry postdates the orphan and
        wins), or a blob tx without its bundle."""
        # chaos seat: the re-injection path crashing mid-reorg (fired
        # OUTSIDE self.lock, like mempool.add)
        inject("mempool.reinject")
        sender = tx.sender()
        if sender is None:
            return False
        if tx.tx_type == TYPE_BLOB and blobs_bundle is None:
            return False
        with self.lock:
            if tx.hash in self.by_hash:
                return False
            existing_queue = self.by_sender.get(sender)
            if existing_queue is not None and \
                    existing_queue.get(tx.nonce) is not None:
                return False
            queue = self.by_sender.setdefault(sender, {})
            queue[tx.nonce] = tx
            self.by_hash[tx.hash] = tx
            self.added_at[tx.hash] = time.monotonic()
            if blobs_bundle is not None:
                self.blobs_bundles[tx.hash] = blobs_bundle
                self._evict_worst_blob()
            else:
                self.txs_order.append(tx.hash)
                self._evict_oldest_regular()
            self.reinjections += 1
            record_mempool_reinjection()
            self._publish_occupancy_locked()
        if _CHAIN_PATH is not None:
            _CHAIN_PATH.tx_admitted(tx.hash)
        # re-injected txs are pending again: the newPendingTransactions
        # subscription and pending filters must see them
        for hook in list(self.on_add):
            hook(tx.hash)
        return True

    def revalidate(self, get_account) -> dict[str, int]:
        """Prune entries the new canonical state invalidated (the reorg
        aftermath): a nonce below the account's (another tx with that
        nonce landed on the winning branch) or a cost the balance no
        longer covers.  Returns {reason: count}; each prune is counted
        in the pool's eviction ledger under its typed reason."""
        with self.lock:
            snapshot = list(self.by_hash.values())
        pruned: dict[str, int] = {}
        accounts: dict[bytes, tuple[int, int]] = {}
        for tx in snapshot:
            sender = tx.sender()
            if sender is None:
                continue
            if sender not in accounts:
                acct = get_account(sender)
                accounts[sender] = (acct.nonce if acct else 0,
                                    acct.balance if acct else 0)
            nonce, balance = accounts[sender]
            reason = None
            if tx.nonce < nonce:
                reason = "nonce_below_account"
            elif tx.gas_limit * tx.max_fee() + tx.value > balance:
                reason = "insufficient_balance"
            if reason is not None:
                self.remove_transaction(tx.hash, reason=reason)
                pruned[reason] = pruned.get(reason, 0) + 1
        return pruned

    def _regular_tx_count(self) -> int:
        return len(self.by_hash) - len(self.blobs_bundles)

    def _evict_oldest_regular(self) -> None:
        """FIFO-evict regular txs past the cap; blob txs never feel
        regular-pool pressure (reference: mempool.rs:462-475)."""
        while self._regular_tx_count() > self.capacity and self.txs_order:
            oldest = self.txs_order.pop(0)
            if oldest in self.by_hash and oldest not in self.blobs_bundles:
                dwell = self._dwell_locked(oldest)
                self._remove_locked(oldest)
                self.evictions["fifo"] = self.evictions.get("fifo", 0) + 1
                record_mempool_eviction("fifo")
                if dwell is not None:
                    observe_time_in_pool(dwell, "fifo")
                if _CHAIN_PATH is not None:
                    _CHAIN_PATH.tx_removed(oldest, "fifo", dwell)

    def _evict_worst_blob(self) -> None:
        """Evict the LEAST INCLUDABLE blob tx past the blob sub-pool cap:
        deepest per-sender nonce offset first (it cannot be included
        until earlier same-sender blobs clear), ties broken by lowest
        blob fee (reference: mempool.rs:477-530)."""
        while len(self.blobs_bundles) > self.blob_capacity:
            min_nonce: dict[bytes, int] = {}
            for h in self.blobs_bundles:
                tx = self.by_hash.get(h)
                if tx is None:
                    continue
                s = tx.sender()
                if s not in min_nonce or tx.nonce < min_nonce[s]:
                    min_nonce[s] = tx.nonce
            worst = None
            worst_key = None
            for h in self.blobs_bundles:
                tx = self.by_hash.get(h)
                if tx is None:
                    continue
                offset = tx.nonce - min_nonce[tx.sender()]
                key = (offset, -(tx.max_fee_per_blob_gas or 0))
                if worst_key is None or key > worst_key:
                    worst_key = key
                    worst = h
            if worst is None:
                break
            dwell = self._dwell_locked(worst)
            self._remove_locked(worst)
            self.evictions["blob_pool_full"] = \
                self.evictions.get("blob_pool_full", 0) + 1
            record_mempool_eviction("blob_pool_full")
            if dwell is not None:
                observe_time_in_pool(dwell, "blob_pool_full")
            if _CHAIN_PATH is not None:
                _CHAIN_PATH.tx_removed(worst, "blob_pool_full", dwell)

    def _dwell_locked(self, tx_hash: bytes) -> float | None:
        """Seconds since admission — read BEFORE ``_remove_locked``
        pops ``added_at``; feeds the reason-labelled time-in-pool
        histogram and the chain-path admission dwell."""
        t0 = self.added_at.get(tx_hash)
        return time.monotonic() - t0 if t0 is not None else None

    def _remove_locked(self, tx_hash: bytes):
        tx = self.by_hash.pop(tx_hash, None)
        if tx is None:
            return
        self.blobs_bundles.pop(tx_hash, None)
        self.added_at.pop(tx_hash, None)
        sender = tx.sender()
        queue = self.by_sender.get(sender)
        if queue and queue.get(tx.nonce) is tx:
            del queue[tx.nonce]
            if not queue:
                del self.by_sender[sender]

    def remove_transaction(self, tx_hash: bytes, reason: str | None = None):
        """Drop a tx.  Every reasoned removal of a present tx feeds the
        reason-labelled time-in-pool histogram (``included`` is the
        admission→inclusion dwell; evictions/prunes/reorg reasons keep
        their own series so they cannot pollute it) and departs the
        chain-path admission queue.  ``reason=None`` is a silent
        administrative removal (no histogram, counted as an untyped
        drop in the stage queue)."""
        with self.lock:
            present = tx_hash in self.by_hash
            dwell = self._dwell_locked(tx_hash) if present else None
            self._remove_locked(tx_hash)
            if present and reason is not None and reason != "included":
                self.evictions[reason] = self.evictions.get(reason, 0) + 1
                record_mempool_eviction(reason)
            if present:
                self._publish_occupancy_locked()
        if present:
            if reason is not None and dwell is not None:
                observe_time_in_pool(dwell, reason)
            if _CHAIN_PATH is not None:
                _CHAIN_PATH.tx_removed(tx_hash, reason or "admin", dwell)

    def stats_json(self, top_k: int = 5) -> dict:
        """Flow-accounting summary for ethrex_health: occupancy,
        admission/rejection/eviction tallies by reason, and the top-k
        deepest per-sender queues (spam/hot-sender visibility)."""
        with self.lock:
            blob = len(self.blobs_bundles)
            depths = sorted(((len(q), s) for s, q in self.by_sender.items()),
                            reverse=True)[:max(0, top_k)]
            return {
                "size": len(self.by_hash),
                "regular": len(self.by_hash) - blob,
                "blob": blob,
                "capacity": self.capacity,
                "blobCapacity": self.blob_capacity,
                "utilization": round(self._utilization(), 6),
                "admitted": self.admitted,
                "replacements": self.replacements,
                "reinjections": self.reinjections,
                "senderSlotCap": self.max_sender_slots,
                "nonceGapLimit": self.max_nonce_gap,
                "rejections": dict(sorted(self.rejections.items())),
                "evictions": dict(sorted(self.evictions.items())),
                "topSenders": [{"sender": "0x" + s.hex(), "txs": n}
                               for n, s in depths],
            }

    def get_transaction(self, tx_hash: bytes) -> Transaction | None:
        return self.by_hash.get(tx_hash)

    def pending(self, base_fee: int, get_nonce) -> list[Transaction]:
        """Executable txs in inclusion order: highest effective tip first,
        but never breaking per-sender nonce order — a heap over each
        sender's *next* executable tx (the reference's fill_transactions
        ordering, crates/blockchain/payload.rs)."""
        import heapq

        with self.lock:
            chains: dict[bytes, list[Transaction]] = {}
            for sender, queue in self.by_sender.items():
                nonce = get_nonce(sender)
                run = []
                while nonce in queue:
                    tx = queue[nonce]
                    if tx.effective_gas_price(base_fee) is None:
                        break
                    run.append(tx)
                    nonce += 1
                if run:
                    chains[sender] = run
            heap = []
            for seq, (sender, run) in enumerate(chains.items()):
                tip = run[0].effective_gas_price(base_fee) - base_fee
                heapq.heappush(heap, (-tip, seq, sender, 0))
            out = []
            while heap:
                _, seq, sender, idx = heapq.heappop(heap)
                run = chains[sender]
                out.append(run[idx])
                if idx + 1 < len(run):
                    tip = run[idx + 1].effective_gas_price(base_fee) - base_fee
                    heapq.heappush(heap, (-tip, seq, sender, idx + 1))
            return out

    def content(self) -> dict:
        with self.lock:
            return {
                sender: dict(queue)
                for sender, queue in self.by_sender.items()
            }

    def split(self, get_nonce) -> tuple[dict, dict]:
        """The pending-vs-queued partition (reference mempool / geth txpool
        semantics): per sender, txs forming a contiguous nonce run from the
        account's current nonce are PENDING (executable); gapped/future
        nonces are QUEUED until the gap fills."""
        with self.lock:
            pending: dict[bytes, dict[int, Transaction]] = {}
            queued: dict[bytes, dict[int, Transaction]] = {}
            for sender, queue in self.by_sender.items():
                nonce = get_nonce(sender)
                run = {}
                while nonce in queue:
                    run[nonce] = queue[nonce]
                    nonce += 1
                rest = {n: tx for n, tx in queue.items() if n not in run}
                if run:
                    pending[sender] = run
                if rest:
                    queued[sender] = rest
            return pending, queued

    def status(self, get_nonce) -> dict:
        pending, queued = self.split(get_nonce)
        return {
            "pending": sum(len(q) for q in pending.values()),
            "queued": sum(len(q) for q in queued.values()),
        }

    def __len__(self):
        return len(self.by_hash)
