"""Embedded network presets: genesis + bootnodes + full fork schedule for
mainnet / sepolia / hoodi (parity: crates/common/config/networks.rs:12-31,
which embeds the same public chain constants at compile time).

`--network hoodi` style preset names resolve here before being treated as
a genesis-file path; each preset carries the complete EIP-2124 fork ladder
(including DAO / glacier / blob-parameter-only points) and the EIP-7840
blob schedule, so fork ids validate against real peers and sync targeting
a live network becomes testable.
"""

from __future__ import annotations

import json
import os

from ..primitives.genesis import Genesis

_HERE = os.path.dirname(os.path.abspath(__file__))

PRESET_NAMES = ("mainnet", "sepolia", "hoodi")


def is_preset(name: str) -> bool:
    return name in PRESET_NAMES


def load_genesis_json(name: str) -> dict:
    if not is_preset(name):
        raise ValueError(f"unknown network preset {name!r}")
    with open(os.path.join(_HERE, "networks", name, "genesis.json")) as f:
        return json.load(f)


def load_network(name: str) -> tuple[Genesis, list[str]]:
    """(Genesis, bootnode enode URLs) for an embedded preset."""
    genesis = Genesis.from_json(load_genesis_json(name))
    with open(os.path.join(_HERE, "networks", name,
                           "bootnodes.json")) as f:
        bootnodes = json.load(f)
    return genesis, list(bootnodes)
